"""Scenario-matrix engine suite (README "Scenario matrix").

Covers the ISSUE 14 tentpole + satellites: the exact Dirichlet-α /
size-imbalance partitioner (per-client counts sum to the corpus,
α→∞ ~IID, small α concentrates, seeded determinism), the vocabulary-
skew generator, persona spec parsing with fail-fast validation (shared
with the ``--chaos`` CLI flag), the degradation contracts, the bench
schema kinds, and end-to-end cells driving the real in-process
federation — including a CTM cell under cohort pacing with the quality
plane on, and a slow-marked crash-persona cell exercising zero-flag
autorecovery inside the scenario engine.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

from gfedntm_tpu.data.loaders import (
    RawCorpus,
    heterogeneous_partition,
    imbalance_weights,
    partition_corpus,
)
from gfedntm_tpu.data.synthetic import (
    apply_vocabulary_skew,
    dominant_topics,
    generate_synthetic_corpus,
)
from gfedntm_tpu.federation.resilience import (
    FaultSpec,
    build_fault_injector,
    known_fault_methods,
    validate_fault_spec,
)
from gfedntm_tpu.scenarios import (
    ScenarioCell,
    baseline_of,
    build_corpora,
    cell_bench_row,
    collect_cell_evidence,
    default_matrix,
    evaluate_contracts,
    fault_specs_for,
    parse_data_persona,
    parse_fault_persona,
    run_cell,
)
from gfedntm_tpu.scenarios.contracts import quorum_floor
from gfedntm_tpu.utils.observability import MetricsLogger

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir,
                 "scripts"),
)
import bench_schema  # noqa: E402


# ---------------------------------------------------------------------------
# the Dirichlet-α / imbalance partitioner (acceptance: exact + tested)
# ---------------------------------------------------------------------------

class TestHeterogeneousPartition:
    LABELS = np.random.default_rng(0).integers(0, 6, 300)

    def _assert_exact(self, shards, n_docs):
        allidx = np.concatenate(shards)
        assert len(allidx) == n_docs
        assert len(np.unique(allidx)) == n_docs  # every doc exactly once

    def test_dirichlet_is_exact(self):
        for alpha in (0.02, 0.5, 10.0, 1e6):
            shards = heterogeneous_partition(
                self.LABELS, 300, 4, alpha=alpha, seed=3
            )
            self._assert_exact(shards, 300)

    def test_alpha_inf_recovers_iid(self):
        """α→∞: near-uniform shard sizes AND near-global class mixture
        per shard."""
        shards = heterogeneous_partition(
            self.LABELS, 300, 4, alpha=1e7, seed=1
        )
        sizes = [len(s) for s in shards]
        assert max(sizes) - min(sizes) < 60  # ~75 each, multinomial noise
        global_frac = np.bincount(self.LABELS, minlength=6) / 300
        for shard in shards:
            frac = np.bincount(self.LABELS[shard], minlength=6) / len(shard)
            assert np.abs(frac - global_frac).max() < 0.2

    def test_small_alpha_concentrates_classes(self):
        shards = heterogeneous_partition(
            self.LABELS, 300, 4, alpha=0.02, seed=1
        )
        self._assert_exact(shards, 300)
        fracs = []
        for cls in np.unique(self.LABELS):
            cls_idx = np.flatnonzero(self.LABELS == cls)
            counts = [np.isin(s, cls_idx).sum() for s in shards]
            fracs.append(max(counts) / max(sum(counts), 1))
        # most of each class lands on ONE client
        assert np.mean(fracs) > 0.7

    def test_seeded_determinism(self):
        a = heterogeneous_partition(self.LABELS, 300, 4, alpha=0.1, seed=9)
        b = heterogeneous_partition(self.LABELS, 300, 4, alpha=0.1, seed=9)
        assert all((x == y).all() for x, y in zip(a, b))
        c = heterogeneous_partition(self.LABELS, 300, 4, alpha=0.1, seed=10)
        assert any((x.shape != y.shape) or (x != y).any()
                   for x, y in zip(a, c))

    def test_size_imbalance_exact_and_ratioed(self):
        shards = heterogeneous_partition(
            None, 4000, 4, size_ratio=20.0, seed=2
        )
        self._assert_exact(shards, 4000)
        sizes = sorted(len(s) for s in shards)
        # multinomial noise around the geometric targets: the realized
        # spread must reflect the ratio's order of magnitude
        assert sizes[-1] / max(sizes[0], 1) > 8.0

    def test_dirichlet_composes_with_imbalance(self):
        shards = heterogeneous_partition(
            self.LABELS, 300, 4, alpha=0.1, size_ratio=50.0, seed=5
        )
        self._assert_exact(shards, 300)

    def test_min_docs_rebalance(self):
        shards = heterogeneous_partition(
            self.LABELS, 300, 5, alpha=0.01, size_ratio=100.0, seed=0,
            min_docs=6,
        )
        self._assert_exact(shards, 300)
        assert all(len(s) >= 6 for s in shards)

    def test_imbalance_weights_ratio(self):
        w = imbalance_weights(4, 25.0)
        assert abs(w.sum() - 1.0) < 1e-12
        assert abs(w[-1] / w[0] - 25.0) < 1e-9
        assert imbalance_weights(3, 1.0) == pytest.approx([1 / 3] * 3)

    def test_validation_errors(self):
        with pytest.raises(ValueError):
            heterogeneous_partition(None, 10, 2, alpha=0.5)  # no labels
        with pytest.raises(ValueError):
            heterogeneous_partition(self.LABELS, 300, 2, alpha=0.0)
        with pytest.raises(ValueError):
            heterogeneous_partition(None, 10, 11, min_docs=1)
        with pytest.raises(ValueError):
            imbalance_weights(3, 0.5)
        with pytest.raises(ValueError):
            heterogeneous_partition(self.LABELS[:10], 300, 2, alpha=1.0)

    def test_partition_corpus_routes_and_aligns(self):
        """The RawCorpus wrapper keeps documents/embeddings/labels
        row-aligned through a heterogeneous split."""
        n = 60
        labels = np.arange(n) % 3
        corpus = RawCorpus(
            documents=[f"doc {i}" for i in range(n)],
            embeddings=np.arange(n, dtype=np.float32)[:, None],
            labels=labels,
        )
        shards = partition_corpus(
            corpus, 3, seed=4, alpha=0.2, size_ratio=5.0
        )
        assert sum(len(s) for s in shards) == n
        for shard in shards:
            for doc, emb, lab in zip(
                shard.documents, shard.embeddings, shard.labels
            ):
                i = int(doc.split()[1])
                assert emb[0] == i and lab == i % 3

    def test_partition_corpus_default_unchanged(self):
        corpus = RawCorpus(documents=[f"d{i}" for i in range(20)])
        shards = partition_corpus(corpus, 4, seed=0)
        assert [len(s) for s in shards] == [5, 5, 5, 5]


class TestVocabularySkew:
    DOCS = ["wd1 wd2 wd3 wd1", "wd2 wd4", "wd1 wd5 wd5"]

    def test_zero_frac_is_identity(self):
        assert apply_vocabulary_skew(self.DOCS, 1, 0.0) == self.DOCS

    def test_full_frac_privatizes_every_type(self):
        skewed = apply_vocabulary_skew(self.DOCS, 2, 1.0)
        for doc in skewed:
            assert all(t.startswith("c2x") for t in doc.split())

    def test_consistent_per_type_and_deterministic(self):
        a = apply_vocabulary_skew(self.DOCS, 1, 0.5, seed=3)
        b = apply_vocabulary_skew(self.DOCS, 1, 0.5, seed=3)
        assert a == b
        # every occurrence of a type maps the same way
        mapping = {}
        for orig, new in zip(self.DOCS, a):
            for o, n in zip(orig.split(), new.split()):
                assert mapping.setdefault(o, n) == n
        # different clients privatize different (seeded) subsets
        c = apply_vocabulary_skew(self.DOCS, 9, 0.5, seed=3)
        assert not any(t.startswith("c1x") for d in c for t in d.split())

    def test_bad_frac_rejected(self):
        with pytest.raises(ValueError):
            apply_vocabulary_skew(self.DOCS, 1, 1.5)

    def test_dominant_topics_labels(self):
        corpus = generate_synthetic_corpus(
            vocab_size=50, n_topics=4, n_docs=30, n_nodes=1,
            frozen_topics=4, seed=0, materialize_docs=False,
        )
        labels = dominant_topics(corpus.nodes[0])
        assert labels.shape == (30,)
        assert labels.min() >= 0 and labels.max() < 4


# ---------------------------------------------------------------------------
# persona specs + fail-fast fault validation (satellite)
# ---------------------------------------------------------------------------

class TestPersonaParsing:
    def test_data_persona_composition(self):
        p = parse_data_persona("dirichlet:0.1+imbalance:20+vocabskew:0.5")
        assert p.alpha == 0.1 and p.size_ratio == 20.0
        assert p.vocab_skew == 0.5
        assert parse_data_persona("iid").alpha is None
        assert parse_data_persona("").spec == "iid"

    def test_data_persona_rejects_typos(self):
        for bad in ("dirchlet:0.1", "dirichlet:0", "imbalance:0.5",
                    "vocabskew:2", "dirichlet:x", "dirichlet"):
            with pytest.raises(ValueError):
                parse_data_persona(bad)

    def test_fault_persona_parse(self):
        assert parse_fault_persona("none").kind == "none"
        assert parse_fault_persona("crash:3").crash_round == 3
        assert parse_fault_persona("slow:0.5").value == 0.5
        for bad in ("crashy:1", "crash", "crash:0", "crash:1.5",
                    "slow:-1", "flap:2.5"):
            with pytest.raises(ValueError):
                parse_fault_persona(bad)

    def test_fault_personas_lower_to_valid_specs(self):
        for spec in ("slow:0.5", "partition:3", "flap:4"):
            persona = parse_fault_persona(spec)
            lowered = fault_specs_for(persona, 3)
            assert lowered
            injector = build_fault_injector(lowered)
            assert injector.pending() > 0
        assert fault_specs_for(parse_fault_persona("crash:2"), 3) == []
        assert fault_specs_for(parse_fault_persona("none"), 3) == []


class TestFaultSpecValidation:
    def test_unknown_method_rejected(self):
        with pytest.raises(ValueError, match="unknown RPC method"):
            validate_fault_spec({"method": "TranStep", "kind": "error"})

    def test_known_methods_cover_services(self):
        known = known_fault_methods()
        assert {"TrainStep", "ApplyAggregate", "PushUpdate", "Infer",
                "*"} <= known

    def test_unknown_kind_and_field_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            validate_fault_spec({"method": "TrainStep", "kind": "explode"})
        with pytest.raises(ValueError, match="unknown fault-spec field"):
            validate_fault_spec({"method": "TrainStep", "dely_s": 1.0})

    def test_negative_delay_and_bad_counts_rejected(self):
        with pytest.raises(ValueError, match="delay_s"):
            FaultSpec(method="TrainStep", kind="delay", delay_s=-0.5)
        with pytest.raises(ValueError, match="times"):
            FaultSpec(method="TrainStep", times=0)
        with pytest.raises(ValueError, match="probability"):
            FaultSpec(method="TrainStep", probability=0.0)

    def test_code_name_resolution(self):
        out = validate_fault_spec({
            "method": "TrainStep", "kind": "error", "code": "ABORTED",
        })
        import grpc

        assert out["code"] is grpc.StatusCode.ABORTED
        with pytest.raises(ValueError, match="StatusCode"):
            validate_fault_spec({
                "method": "TrainStep", "code": "NOT_A_CODE",
            })

    def test_wrong_typed_value_is_usage_error_not_traceback(self):
        """A JSON string where a number is expected must surface as the
        same ValueError usage error the CLI turns into SystemExit, not a
        raw TypeError traceback."""
        with pytest.raises(ValueError, match="bad fault-spec value"):
            validate_fault_spec({
                "method": "TrainStep", "kind": "delay", "delay_s": "0.5",
            })
        with pytest.raises(ValueError, match="fault spec #0"):
            build_fault_injector(
                '[{"method": "TrainStep", "kind": "delay", '
                '"delay_s": "0.5"}]'
            )

    def test_builder_json_and_index_in_error(self):
        with pytest.raises(ValueError, match="not valid JSON"):
            build_fault_injector("[{")
        with pytest.raises(ValueError, match="fault spec #1"):
            build_fault_injector(json.dumps([
                {"method": "TrainStep"},
                {"method": "Nope"},
            ]))
        with pytest.raises(ValueError, match="JSON list"):
            build_fault_injector('{"method": "TrainStep"}')

    def test_cli_chaos_flag_fails_fast(self, tmp_path):
        """A typo'd --chaos spec exits with a usage error at startup —
        never an inert injector."""
        from gfedntm_tpu.cli import build_parser, run_server

        args = build_parser().parse_args([
            "--id", "0", "--save_dir", str(tmp_path),
            "--chaos", '[{"method": "TranStep", "kind": "drop"}]',
        ])
        from gfedntm_tpu.config import GfedConfig

        with pytest.raises(SystemExit, match="--chaos"):
            run_server(args, GfedConfig())

    def test_cli_chaos_flag_accepts_valid_spec_shape(self):
        """The documented partition example parses through the shared
        validator."""
        spec = [{"method": "*", "kind": "partition", "peer": "client2",
                 "delay_s": 5}]
        injector = build_fault_injector(json.dumps(spec))
        assert injector.pending() == 1


# ---------------------------------------------------------------------------
# cells, contracts, evidence
# ---------------------------------------------------------------------------

def _evidence(**over):
    base = dict(
        finished=True,
        betas_finite=True,
        rounds=8,
        averaged_push_clients=[3, 3, 2, 2, 1],
        quorum_skips=1,
        counters={"codec_ref_miss": 0.0, "rpcs_deduplicated": 0.0},
        npmi_final=-0.30,
        quality_rounds=8,
        recovery=None,
    )
    base.update(over)
    return base


class TestContracts:
    CELL = ScenarioCell("t", quorum_fraction=0.5, npmi_tol=0.1)

    def test_all_green_without_baseline(self):
        verdicts = evaluate_contracts(self.CELL, _evidence())
        assert all(v["ok"] for v in verdicts.values())
        assert "recovery" not in verdicts  # no crash persona

    def test_unfinished_or_nonfinite_fails(self):
        v = evaluate_contracts(self.CELL, _evidence(finished=False))
        assert not v["completes"]["ok"]
        v = evaluate_contracts(self.CELL, _evidence(betas_finite=False))
        assert not v["completes"]["ok"]

    def test_quorum_degeneration_fails(self):
        # majority of averaged rounds below the floor = degenerate
        v = evaluate_contracts(
            self.CELL, _evidence(averaged_push_clients=[1, 1, 1, 3])
        )
        assert not v["quorum"]["ok"]
        v = evaluate_contracts(
            self.CELL, _evidence(averaged_push_clients=[])
        )
        assert not v["quorum"]["ok"]

    def test_quorum_floor_per_pacing(self):
        assert quorum_floor(ScenarioCell("a")) == 2  # ceil(.5 * 3)
        assert quorum_floor(ScenarioCell("b", pacing="cohort:2")) == 1
        assert quorum_floor(ScenarioCell("c", pacing="async:2")) == 1
        assert quorum_floor(
            ScenarioCell("d", n_clients=4, quorum_fraction=0.75)
        ) == 3

    def test_counter_drift_fails_against_baseline(self):
        baseline = _evidence()
        v = evaluate_contracts(
            self.CELL,
            _evidence(counters={"codec_ref_miss": 2.0,
                                "rpcs_deduplicated": 0.0}),
            baseline,
        )
        assert not v["counters_clean"]["ok"]
        assert "codec_ref_miss" in v["counters_clean"]["detail"]

    def test_npmi_tolerance_vs_baseline(self):
        baseline = _evidence(npmi_final=-0.25)
        # delta 0.15 > tol 0.1: violation
        v = evaluate_contracts(
            self.CELL, _evidence(npmi_final=-0.40), baseline
        )
        assert not v["npmi_tolerance"]["ok"]
        # delta 0.05 <= tol 0.1: within the declared tolerance
        v = evaluate_contracts(
            self.CELL, _evidence(npmi_final=-0.30), baseline
        )
        assert v["npmi_tolerance"]["ok"]

    def test_missing_npmi_fails(self):
        v = evaluate_contracts(self.CELL, _evidence(npmi_final=None))
        assert not v["npmi_tolerance"]["ok"]

    def test_crash_recovery_contract(self):
        cell = ScenarioCell("t", fault="crash:3")
        good = _evidence(recovery={
            "recovered": True, "resumed_round": 3, "killed_round": 3,
        })
        v = evaluate_contracts(cell, good)
        assert v["recovery"]["ok"]
        for bad in (
            None,
            {"recovered": False, "resumed_round": None, "killed_round": 3},
            {"recovered": True, "resumed_round": 1, "killed_round": 4},
        ):
            v = evaluate_contracts(cell, _evidence(recovery=bad))
            assert not v["recovery"]["ok"], bad


class TestCollectEvidence:
    def _records(self):
        t = 1000.0
        server = [
            {"event": "span", "time": t, "node": "server", "name": "push",
             "span_id": "a", "parent_id": None, "seconds": 0.1,
             "clients": 3},
            {"event": "span", "time": t, "node": "server", "name": "push",
             "span_id": "b", "parent_id": None, "seconds": 0.1,
             "clients": 2},
            {"event": "span", "time": t, "node": "server", "name": "poll",
             "span_id": "c", "parent_id": None, "seconds": 0.1,
             "clients": 9},
            {"event": "quorum_skip", "time": t, "node": "server",
             "round": 2, "got": 1, "needed": 2},
            {"event": "quality_computed", "time": t, "node": "server",
             "round": 1, "npmi": -0.4, "diversity": 0.8},
            {"event": "quality_computed", "time": t, "node": "server",
             "round": 2, "npmi": -0.3, "diversity": 0.8},
            {"event": "server_recovered", "time": t, "node": "server",
             "round": 2, "source": "journal"},
            {"event": "metrics_snapshot", "time": t, "node": "server",
             "metrics": {
                 "codec_ref_miss": {"type": "counter", "value": 1.0},
                 "other": {"type": "counter", "value": 9.0},
             }},
        ]
        client = [
            {"event": "metrics_snapshot", "time": t, "node": "client1",
             "metrics": {
                 "codec_ref_miss": {"type": "counter", "value": 0.5},
                 "rpcs_deduplicated": {"type": "counter", "value": 2.0},
             }},
        ]
        return [server, client]

    def test_collection(self):
        ev = collect_cell_evidence(
            self._records(), finished=True, betas_finite=True, rounds=4,
        )
        assert ev["averaged_push_clients"] == [3, 2]  # push spans only
        assert ev["quorum_skips"] == 1
        assert ev["counters"]["codec_ref_miss"] == 1.5  # summed streams
        assert ev["counters"]["rpcs_deduplicated"] == 2.0
        assert ev["npmi_final"] == -0.3  # last round's value
        assert ev["quality_rounds"] == 2
        assert ev["server_recovered_events"] == 1

    def test_only_last_snapshot_counts(self):
        records = self._records()
        records[0].append({
            "event": "metrics_snapshot", "time": 1001.0, "node": "server",
            "metrics": {
                "codec_ref_miss": {"type": "counter", "value": 4.0},
            },
        })
        ev = collect_cell_evidence(records)
        assert ev["counters"]["codec_ref_miss"] == 4.5


class TestMatrixAndSchema:
    def test_default_matrix_shape(self):
        cells = default_matrix()
        names = [c.name for c in cells]
        assert len(cells) >= 12
        assert len(set(names)) == len(names)
        # the acceptance headline: dirichlet data x crash fault x cohort
        assert any(
            c.data_persona.alpha is not None
            and c.fault_persona.kind == "crash"
            and c.pacing.startswith("cohort")
            for c in cells
        )
        # every fault persona kind appears
        kinds = {c.fault_persona.kind for c in cells}
        assert {"none", "slow", "partition", "flap", "crash",
                "relaycrash", "relayloss"} <= kinds
        # both workloads appear
        assert {c.workload for c in cells} == {"avitm", "ctm"}
        # every faulted cell has its no-fault baseline twin in-matrix —
        # except the hierarchical cells, whose pacing axes are tuned to
        # the relay-kill races (stretched runway) and so share no policy
        # key with any flat cell: run_matrix synthesizes their flat
        # twins into the batch (covered by test_run_matrix paths).
        from gfedntm_tpu.scenarios.personas import RELAY_KINDS

        keys = {c.policy_key() for c in cells
                if c.fault_persona.kind == "none"}
        for c in cells:
            kind = c.fault_persona.kind
            if kind != "none" and kind not in RELAY_KINDS:
                assert c.policy_key() in keys, c.name

    def test_baseline_of(self):
        cell = ScenarioCell("x", fault="crash:3")
        twin = baseline_of(cell)
        assert twin.fault == "none"
        assert twin.policy_key() == cell.policy_key()
        assert baseline_of(twin) is None

    def test_shrink_keeps_crash_reachable(self):
        cell = ScenarioCell("x", fault="crash:5").shrink()
        assert cell.fault_persona.crash_round <= 2
        assert cell.total_docs < ScenarioCell("x").total_docs

    def test_cell_rejects_bad_axes(self):
        with pytest.raises(ValueError):
            ScenarioCell("x", workload="lda")
        with pytest.raises(ValueError):
            ScenarioCell("x", data="dirchlet:1")
        with pytest.raises(ValueError):
            ScenarioCell("x", fault="crashy:1")

    def test_bench_schema_kinds(self):
        row = {
            "metric": "scenario", "cell": "c", "workload": "avitm",
            "data_persona": "iid", "fault_persona": "none",
            "pacing": "sync", "aggregator": "fedavg", "npmi": -0.3,
            "baseline_npmi": -0.3, "npmi_tol": 0.35, "contracts": {},
            "ok": True, "seconds": 1.0,
        }
        assert bench_schema.validate(row, "scenario") == []
        bad = dict(row)
        del bad["contracts"]
        assert bench_schema.validate(bad, "scenario")
        artifact = {
            "bench": "scenario_matrix", "rev": "abc", "cells": [row],
            "acceptance": {},
        }
        assert bench_schema.validate(artifact, "scenario_bench") == []

    def test_build_corpora_personas(self):
        cell = ScenarioCell(
            "x", data="dirichlet:0.1+imbalance:10+vocabskew:0.6",
            total_docs=90,
        )
        corpora, ref_docs = build_corpora(cell)
        assert len(corpora) == cell.n_clients
        assert sum(len(c) for c in corpora) == 90
        assert len(ref_docs) == 90
        sizes = sorted(len(c) for c in corpora)
        assert sizes[-1] > sizes[0]  # imbalance
        # vocab skew: client-private namespaces present and disjoint
        tok1 = {t for d in corpora[0].documents for t in d.split()}
        assert any(t.startswith("c1x") for t in tok1)
        assert not any(t.startswith("c2x") for t in tok1)
        # reference corpus is the pre-skew pooled corpus
        assert not any(
            t.startswith("c") for d in ref_docs for t in d.split()
        )

    def test_build_corpora_ctm_embeddings(self):
        corpora, _ = build_corpora(
            ScenarioCell("x", workload="ctm", total_docs=60)
        )
        for c in corpora:
            assert c.embeddings is not None
            assert c.embeddings.shape == (len(c.documents), 12)


# ---------------------------------------------------------------------------
# end-to-end cells (real in-process federation over gRPC)
# ---------------------------------------------------------------------------

def _run_named_cell(name, tmp_path, metrics=None):
    cells = {c.name: c for c in default_matrix()}
    return run_cell(
        cells[name].shrink(), str(tmp_path / name), metrics=metrics,
    )


@pytest.mark.chaos
def test_cell_e2e_fast_iid_sync(tmp_path):
    """One fast cell end to end: the federation runs, every contract is
    green, the scenario lifecycle events land on the harness stream,
    the bench row validates against the schema — and a RERUN into the
    same workdir starts from a clean slate (a reused dir must not
    append to the prior run's streams: stale evidence could outvote a
    fresh regression in the contract checks)."""
    from dataclasses import replace

    metrics = MetricsLogger(
        str(tmp_path / "harness.jsonl"), node="scenarios", validate=True,
        keep_records=True,
    )
    cells = {c.name: c for c in default_matrix()}
    cell = replace(
        cells["iid-sync-fedavg"].shrink(), num_epochs=1, total_docs=36,
    )
    res = run_cell(cell, str(tmp_path / cell.name), metrics=metrics)
    metrics.close()
    assert res.ok, res.contracts
    assert res.evidence["npmi_final"] is not None
    assert res.evidence["quality_rounds"] >= 1
    row = cell_bench_row(res)
    assert bench_schema.validate(row, "scenario") == []
    started = metrics.events("scenario_cell_started")
    finished = metrics.events("scenario_cell_finished")
    contracts = metrics.events("scenario_contract")
    assert len(started) == 1 and len(finished) == 1
    assert finished[0]["ok"] is True
    assert {c["contract"] for c in contracts} == set(res.contracts)

    # Rerun into the SAME workdir: evidence must cover this run alone.
    res2 = run_cell(cell, str(tmp_path / cell.name))
    assert res2.ok, res2.contracts
    assert len(res2.evidence["averaged_push_clients"]) == len(
        res.evidence["averaged_push_clients"]
    )


@pytest.mark.slow
@pytest.mark.chaos
def test_cell_e2e_ctm_cohort_quality(tmp_path):
    """Satellite: CTM as a federated scenario under cohort pacing with
    the quality plane on — finite betas and a rendered quality report.
    Slow-marked for the tier-1 budget; the net-path twin lives in
    test_federation_net.py and the SCENARIO=1 stage drives cells
    end-to-end."""
    from gfedntm_tpu.utils.observability import (
        format_quality_report,
        read_metrics,
        summarize_model_quality,
    )

    res = _run_named_cell("ctm-dir01-cohort", tmp_path)
    assert res.ok, res.contracts
    assert res.evidence["betas_finite"]
    records = read_metrics(
        os.path.join(res.workdir, "server", "metrics.jsonl")
    )
    summary = summarize_model_quality(records)
    assert summary["quality"], "no quality rounds recorded"
    report = format_quality_report(summary)
    assert "npmi" in report.lower() or "coherence" in report.lower()


@pytest.mark.slow
@pytest.mark.chaos
def test_cell_e2e_crash_persona_autorecovers(tmp_path):
    """The crash persona inside the scenario engine: mid-run server
    kill, replacement autorecovers from the journal, clients ride
    session tokens, contracts green including recovery."""
    res = _run_named_cell("iid-crash-sync", tmp_path)
    assert res.ok, res.contracts
    rec = res.evidence["recovery"]
    assert rec["recovered"] and rec["source"] == "journal"
    assert res.evidence["server_recovered_events"] >= 1
    assert res.evidence["counters"]["codec_ref_miss"] == 0.0


def test_scenarios_cli_list_and_unknown_cell(capsys, tmp_path):
    from gfedntm_tpu.cli import run_scenarios

    assert run_scenarios(["--list"]) == 0
    out = capsys.readouterr().out
    assert "dir01-crash-cohort" in out
    with pytest.raises(SystemExit, match="unknown cell"):
        run_scenarios([
            "--cells", "no-such-cell", "--workdir", str(tmp_path),
        ])
