"""ELBO parity vs the PyTorch reference formulas (BASELINE.json metric).

The torch side re-implements the math of ``avitm.py:168-229`` / ``ctm.py:182-238``
from the formulas; identical random tensors must produce identical losses.
"""

import numpy as np
import pytest
import torch

from gfedntm_tpu.models import losses


def torch_avitm_loss(inputs, word_dists, prior_mean, prior_variance,
                     posterior_mean, posterior_variance, posterior_log_variance):
    n_components = posterior_mean.shape[1]
    var_division = torch.sum(posterior_variance / prior_variance, dim=1)
    diff_means = prior_mean - posterior_mean
    diff_term = torch.sum((diff_means * diff_means) / prior_variance, dim=1)
    logvar_det_division = prior_variance.log().sum() - posterior_log_variance.sum(dim=1)
    KL = 0.5 * (var_division + diff_term - n_components + logvar_det_division)
    RL = -torch.sum(inputs * torch.log(word_dists + 1e-10), dim=1)
    return KL, RL, (KL + RL).sum()


def _rand_inputs(rng, batch=16, vocab=30, k=7):
    inputs = rng.integers(0, 5, size=(batch, vocab)).astype(np.float32)
    logits = rng.normal(size=(batch, vocab)).astype(np.float32)
    word_dists = np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)
    prior_mean = rng.normal(size=(k,)).astype(np.float32)
    prior_variance = rng.uniform(0.5, 1.5, size=(k,)).astype(np.float32)
    post_mean = rng.normal(size=(batch, k)).astype(np.float32)
    post_logvar = rng.normal(scale=0.3, size=(batch, k)).astype(np.float32)
    post_var = np.exp(post_logvar)
    return inputs, word_dists, prior_mean, prior_variance, post_mean, post_var, post_logvar


def test_avitm_loss_matches_torch(rng):
    args = _rand_inputs(rng)
    t_args = [torch.from_numpy(a) for a in args]
    KL_t, RL_t, total_t = torch_avitm_loss(*t_args)

    kl = losses.gaussian_kl(args[2], args[3], args[4], args[5], args[6])
    rl = losses.reconstruction_loss(args[0], args[1])
    total = losses.avitm_loss(*args)

    np.testing.assert_allclose(np.asarray(kl), KL_t.numpy(), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(rl), RL_t.numpy(), rtol=1e-5)
    np.testing.assert_allclose(float(total), float(total_t), rtol=1e-5)


def test_ctm_loss_beta_weight_and_labels(rng):
    args = _rand_inputs(rng)
    t_args = [torch.from_numpy(a) for a in args]
    KL_t, RL_t, _ = torch_avitm_loss(*t_args)
    beta_w = 0.7

    batch = args[0].shape[0]
    n_labels = 4
    est = rng.normal(size=(batch, n_labels)).astype(np.float32)
    onehot = np.eye(n_labels, dtype=np.float32)[rng.integers(0, n_labels, batch)]

    expected = (beta_w * KL_t + RL_t).sum()
    ce = torch.nn.CrossEntropyLoss()(
        torch.from_numpy(est), torch.argmax(torch.from_numpy(onehot), 1)
    )
    expected = expected + ce

    got = losses.ctm_loss(
        *args, beta_weight=beta_w, estimated_labels=est, labels_onehot=onehot
    )
    np.testing.assert_allclose(float(got), float(expected), rtol=1e-5)


def test_sample_mask_equals_short_batch(rng):
    """A masked padded batch must give the same sum as the truncated batch."""
    args = _rand_inputs(rng, batch=10)
    short = [a[:6] if a.ndim == 2 else a for a in args]
    mask = np.zeros(10, np.float32)
    mask[:6] = 1.0
    full = losses.avitm_loss(*args, sample_mask=mask)
    trunc = losses.avitm_loss(*short)
    np.testing.assert_allclose(float(full), float(trunc), rtol=1e-5)


@pytest.mark.parametrize("n", [1, 3])
def test_kl_zero_when_posterior_equals_prior(n):
    k = 5
    pm = np.zeros(k, np.float32)
    pv = np.full(k, 0.8, np.float32)
    post_m = np.tile(pm, (n, 1))
    post_lv = np.tile(np.log(pv), (n, 1))
    kl = losses.gaussian_kl(pm, pv, post_m, np.exp(post_lv), post_lv)
    np.testing.assert_allclose(np.asarray(kl), np.zeros(n), atol=1e-6)
