"""Fleet telemetry plane tests (ISSUE 16): the exact cross-node merge
primitive, delta-encoded piggyback shipping (loss-tolerant by periodic
full reports), the server-side FleetRegistry (replay dedup, corrupt-bytes
hygiene, cardinality guard, bounded /status.fleet summary), fleet
Prometheus exposition, the relay tier's pre-reduced shard report, and the
acceptance e2e: a live simulated federation whose server-side fleet-merged
histogram equals the offline merge of the clients' own JSONL snapshots
bucket-for-bucket — under sync, cohort, and push pacing.
"""

import json
import urllib.request
import zlib

import pytest

from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.federation.relay import RelayNode
from gfedntm_tpu.federation.simfleet import make_sim_fleet
from gfedntm_tpu.utils.observability import (
    FleetRegistry,
    MetricRegistry,
    MetricsLogger,
    OpsServer,
    TelemetryShipper,
    decode_telemetry_report,
    encode_telemetry_report,
    merge_metric_snapshots,
    merge_node_snapshots,
    read_metrics,
    render_fleet_prometheus,
    summarize_metrics,
)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def _observe_series(registry, values):
    h = registry.histogram("local_step_s")
    for v in values:
        h.observe(v)


# ---- exact merge primitive ---------------------------------------------------

class TestMergePrimitive:
    def test_counters_sum(self):
        out = merge_metric_snapshots(
            {"type": "counter", "value": 3.0},
            {"type": "counter", "value": 4.5},
        )
        assert out == {"type": "counter", "value": 7.5}

    def test_gauges_last_write_wins_but_none_never_clobbers(self):
        a = {"type": "gauge", "value": 1.0}
        b = {"type": "gauge", "value": 2.0}
        none = {"type": "gauge", "value": None}
        assert merge_metric_snapshots(a, b)["value"] == 2.0
        assert merge_metric_snapshots(a, none)["value"] == 1.0
        assert merge_metric_snapshots(none, b)["value"] == 2.0

    def test_histograms_add_bucket_wise_exactly(self):
        ra, rb = MetricRegistry(), MetricRegistry()
        _observe_series(ra, [0.001, 0.002, 5.0])
        _observe_series(rb, [0.002, 0.5])
        a = ra.snapshot()["local_step_s"]
        b = rb.snapshot()["local_step_s"]
        out = merge_metric_snapshots(a, b)
        assert out["count"] == 5
        assert out["sum"] == pytest.approx(a["sum"] + b["sum"])
        assert out["counts"] == [
            x + y for x, y in zip(a["counts"], b["counts"])
        ]
        assert out["min"] == min(a["min"], b["min"])
        assert out["max"] == max(a["max"], b["max"])

    def test_empty_histogram_merge_keeps_min_max_contract(self):
        ra, rb = MetricRegistry(), MetricRegistry()
        ra.histogram("h")  # never observed: snapshot omits min/max
        rb.histogram("h")
        empty = ra.snapshot()["h"]
        assert "min" not in empty
        both_empty = merge_metric_snapshots(empty, rb.snapshot()["h"])
        assert both_empty["count"] == 0 and "min" not in both_empty
        rb.histogram("h").observe(0.01)
        one_sided = merge_metric_snapshots(empty, rb.snapshot()["h"])
        assert one_sided["min"] == one_sided["max"] == 0.01

    def test_mismatches_raise(self):
        with pytest.raises(ValueError):
            merge_metric_snapshots(
                {"type": "counter", "value": 1.0},
                {"type": "gauge", "value": 1.0},
            )
        h = {"type": "histogram", "count": 0, "sum": 0.0,
             "edges": [1.0], "counts": [0, 0]}
        g = {"type": "histogram", "count": 0, "sum": 0.0,
             "edges": [2.0], "counts": [0, 0]}
        with pytest.raises(ValueError):
            merge_metric_snapshots(h, g)

    def test_node_merge_drops_unmergeable_and_is_deterministic(self):
        nodes = {
            "client2": {"m": {"type": "counter", "value": 1.0},
                        "g": {"type": "gauge", "value": 2.0}},
            "client1": {"m": {"type": "gauge", "value": 9.0},
                        "g": {"type": "gauge", "value": 1.0}},
        }
        merged = merge_node_snapshots(nodes)
        # mixed-type metric dropped, never poisons the view
        assert "m" not in merged
        # node-sorted iteration: client2's gauge write wins
        assert merged["g"]["value"] == 2.0


# ---- wire form + delta shipper ----------------------------------------------

class TestTelemetryShipper:
    def test_wire_roundtrip_and_garbage_rejection(self):
        nodes = {"client1": {"c": {"type": "counter", "value": 2.0}}}
        data = encode_telemetry_report(nodes, full=True)
        report = decode_telemetry_report(data)
        assert report["nodes"] == nodes and report["full"] is True
        for garbage in (b"\x00junk", zlib.compress(b"[1,2]"),
                        zlib.compress(b"{}")):
            with pytest.raises(ValueError):
                decode_telemetry_report(garbage)

    def test_ships_only_changed_metrics_and_empty_when_idle(self):
        reg = MetricRegistry()
        reg.counter("a").inc()
        reg.counter("b").inc()
        shipper = TelemetryShipper(registry=reg, node="client1")
        first = decode_telemetry_report(shipper.build())
        assert first["full"] is True
        assert set(first["nodes"]["client1"]) == {"a", "b"}
        # idle: the proto field stays empty, costing nothing on the wire
        assert shipper.build() == b""
        reg.counter("b").inc()
        delta = decode_telemetry_report(shipper.build())
        assert delta["full"] is False
        assert set(delta["nodes"]["client1"]) == {"b"}

    def test_periodic_full_report_heals_lost_deltas(self):
        reg = MetricRegistry()
        shipper = TelemetryShipper(registry=reg, node="client1",
                                   full_every=4)
        fleet = FleetRegistry()
        for i in range(9):
            reg.counter("steps").inc()
            reg.gauge("last").set(float(i))
            data = shipper.build()
            # a lossy network: every other delta report vanishes; full
            # reports (ships 0, 4, 8) happen to survive here, which is
            # exactly the healing mechanism under test
            if i % 2 == 0:
                fleet.ingest_bytes(data)
        # the surviving ship at i=8 was a FULL report: receiver state
        # converged to the sender's registry despite the losses
        assert fleet.node_snapshots()["client1"] == reg.snapshot()


# ---- FleetRegistry -----------------------------------------------------------

class TestFleetRegistry:
    def test_replayed_report_is_a_no_op(self):
        reg = MetricRegistry()
        reg.counter("steps").inc(3)
        data = encode_telemetry_report(
            {"client1": reg.snapshot()}, full=False
        )
        fleet = FleetRegistry()
        assert fleet.ingest_bytes(data)
        once = fleet.merged()
        # an RPC replay re-delivers the same report: replace semantics
        # make the second ingest a no-op, never a double count
        assert fleet.ingest_bytes(data)
        assert fleet.merged() == once
        assert once["steps"]["value"] == 3.0

    def test_corrupt_bytes_counted_never_raised(self):
        m = MetricsLogger(validate=True)
        fleet = FleetRegistry(metrics=m)
        assert fleet.ingest_bytes(b"\x99not-a-report") is False
        assert fleet.ingest_bytes(b"") is False  # empty field: not an error
        assert m.registry.counter("fleet_reports_invalid").value == 1
        assert fleet.node_snapshots() == {}

    def test_node_cardinality_guard_is_loud_once_per_node(self):
        m = MetricsLogger(validate=True)
        fleet = FleetRegistry(metrics=m, max_nodes=2)
        snap = {"c": {"type": "counter", "value": 1.0}}
        assert fleet.ingest("client1", snap)
        assert fleet.ingest("client2", snap)
        assert not fleet.ingest("client3", snap)
        assert not fleet.ingest("client3", snap)
        assert len(fleet.node_snapshots()) == 2
        assert m.registry.counter("fleet_reports_dropped").value == 2
        # one fleet_overflow event per (node, reason), not per report
        events = m.events("fleet_overflow")
        assert len(events) == 1
        assert events[0]["node"] == "client3"
        assert events[0]["reason"] == "max_nodes"

    def test_series_cardinality_guard(self):
        m = MetricsLogger(validate=True)
        fleet = FleetRegistry(metrics=m, max_series_per_node=2)
        ok = fleet.ingest("client1", {
            f"m{i}": {"type": "counter", "value": 1.0} for i in range(5)
        })
        assert not ok
        assert len(fleet.node_snapshots()["client1"]) == 2
        # the admitted series still update in place under the cap
        assert fleet.ingest(
            "client1", {"m0": {"type": "counter", "value": 7.0}}
        )
        assert fleet.node_snapshots()["client1"]["m0"]["value"] == 7.0
        assert m.events("fleet_overflow")[0]["reason"] == \
            "max_series_per_node"

    def test_summary_stays_bounded_at_1k_nodes(self):
        fleet = FleetRegistry(max_nodes=2048)
        reg = MetricRegistry()
        _observe_series(reg, [0.01, 0.02])
        snap = reg.snapshot()
        for i in range(1000):
            fleet.ingest(f"client{i}", snap)
        summary = fleet.summary()
        assert summary["nodes"] == 1000
        assert summary["series"] == 1000 * len(snap)
        assert len(summary["top_nodes"]) == 8
        assert len(summary["histograms"]) <= 8
        # the /status.fleet payload is O(top_k), not O(fleet)
        assert len(json.dumps(summary)) < 8192
        merged = fleet.merged()
        assert merged["local_step_s"]["count"] == 2000


# ---- fleet Prometheus exposition --------------------------------------------

class TestFleetPrometheus:
    def test_fleet_and_node_families_with_labels(self):
        ra, rb = MetricRegistry(), MetricRegistry()
        ra.counter("steps").inc(2)
        _observe_series(ra, [0.01])
        rb.counter("steps").inc(3)
        _observe_series(rb, [0.02])
        text = render_fleet_prometheus(
            {"client1": ra.snapshot(), "client2": rb.snapshot()}
        )
        # exact cross-node merge in the fleet families
        assert "gfedntm_fleet_steps_total 5.0" in text
        assert "gfedntm_fleet_local_step_s_count 2" in text
        # per-node series carry the node label
        assert 'gfedntm_node_steps_total{node="client1"} 2.0' in text
        assert 'gfedntm_node_steps_total{node="client2"} 3.0' in text
        assert 'gfedntm_node_local_step_s_count{node="client1"} 1' in text

    def test_node_series_cap_exports_overflow_counter(self):
        nodes = {
            f"client{i}": {"steps": {"type": "counter", "value": 1.0}}
            for i in range(6)
        }
        text = render_fleet_prometheus(nodes, max_series=4)
        assert text.count("gfedntm_node_steps_total{") == 4
        assert ('gfedntm_node_series_overflow_total{family="steps"} 2'
                in text)


# ---- ops endpoints -----------------------------------------------------------

class TestFleetOpsEndpoints:
    def test_metrics_status_fleet_and_alerts_routes(self):
        reg = MetricRegistry()
        reg.counter("rounds").inc()
        fleet = FleetRegistry()
        fleet.ingest("client1", {"steps": {"type": "counter",
                                           "value": 4.0}})
        ops = OpsServer(
            registry=reg, fleet=fleet,
            alerts_fn=lambda: {"alerts": [], "firing": 0},
        )
        port = ops.start()
        try:
            base = f"http://127.0.0.1:{port}"
            code, body = _get(f"{base}/metrics")
            text = body.decode()
            assert code == 200
            assert "gfedntm_rounds_total 1.0" in text
            assert "gfedntm_fleet_steps_total 4.0" in text
            assert 'gfedntm_node_steps_total{node="client1"} 4.0' in text
            code, body = _get(f"{base}/status.fleet")
            assert code == 200
            assert json.loads(body)["nodes"] == 1
            code, body = _get(f"{base}/alerts")
            assert code == 200
            assert json.loads(body) == {"alerts": [], "firing": 0}
        finally:
            ops.stop()


# ---- offline summarize: cross-node correctness ------------------------------

class TestSummarizeCrossNode:
    def test_same_metric_name_across_nodes_merges_not_clobbers(self):
        records = []
        for node, values in (("client1", [0.01, 0.02]),
                             ("client2", [0.02, 0.03, 0.04])):
            m = MetricsLogger(node=node)
            _observe_series(m.registry, values)
            m.registry.counter("steps").inc(len(values))
            records.append(m.snapshot_registry())
        s = summarize_metrics(records)
        assert s["step_time"]["local_step_s"]["count"] == 5
        assert s["counters"]["steps"] == 5.0


# ---- relay tier: pre-reduced shard report -----------------------------------

class TestRelayShardReport:
    def test_relay_merged_shard_report_equals_flat_merge(self):
        # Socketless: the relay's telemetry pipeline is plain objects —
        # members' piggybacked reports land in the shard FleetRegistry,
        # and the upstream shipper sends ONE merged relayN:shard entry.
        relay = RelayNode(relay_id=3, upstream_address="unused:0",
                          min_members=2)
        members = {}
        for cid in (1, 2):
            m = MetricsLogger(node=f"client{cid}")
            _observe_series(m.registry, [0.001 * (cid + k)
                                         for k in range(4)])
            m.registry.counter("steps").inc(4)
            members[cid] = m
            shipper = TelemetryShipper(registry=m.registry,
                                       node=f"client{cid}")
            relay.fleet.ingest_bytes(shipper.build())

        root = FleetRegistry()
        root.ingest_bytes(relay._shipper.build())
        # root cardinality is O(relays): one shard node, never members
        assert set(root.node_snapshots()) == {"relay3:shard"}
        flat = merge_node_snapshots({
            f"client{cid}": m.registry.snapshot()
            for cid, m in members.items()
        })
        merged = root.merged()
        assert merged["steps"]["value"] == flat["steps"]["value"] == 8.0
        assert merged["local_step_s"] == flat["local_step_s"]


class TestRelayBounceFullReship:
    def test_respawned_relay_first_build_heals_root_view(self):
        # A relay crash loses the shipper's delta baseline AND whatever
        # shard deltas were in flight. The respawn contract: a fresh
        # shipper's FIRST build is FULL, and each member's
        # token-reconnect re-ships its FULL report into the new shard
        # registry — so after one post-bounce ship the root's merged
        # view equals the offline flat merge bucket-for-bucket, with
        # nothing double-counted and nothing missing.
        root = FleetRegistry()
        members = {}
        for cid in (1, 2):
            m = MetricsLogger(node=f"client{cid}")
            _observe_series(m.registry, [0.001 * (cid + k)
                                         for k in range(3)])
            m.registry.counter("steps").inc(3)
            members[cid] = m

        relay = RelayNode(relay_id=7, upstream_address="unused:0",
                          min_members=2)
        for cid, m in members.items():
            relay.fleet.ingest_bytes(TelemetryShipper(
                registry=m.registry, node=f"client{cid}").build())
        root.ingest_bytes(relay._shipper.build())  # FULL
        # Members progress; the pre-crash relay ships a delta the crash
        # will orphan on the root (its baseline dies with the process).
        for cid, m in members.items():
            _observe_series(m.registry, [0.01 * cid])
            m.registry.counter("steps").inc(1)
            relay.fleet.ingest_bytes(TelemetryShipper(
                registry=m.registry, node=f"client{cid}").build())
        root.ingest_bytes(relay._shipper.build())

        # SIGKILL-equivalent: the relay object is discarded. The respawn
        # holds a FRESH shipper; members re-ship FULL reports on their
        # token-reconnects (more progress happened while it was down).
        relay2 = RelayNode(relay_id=7, upstream_address="unused:0",
                           min_members=2)
        for cid, m in members.items():
            _observe_series(m.registry, [0.02 * cid, 0.03])
            m.registry.counter("steps").inc(2)
            relay2.fleet.ingest_bytes(TelemetryShipper(
                registry=m.registry, node=f"client{cid}").build())
        root.ingest_bytes(relay2._shipper.build())  # fresh shipper: FULL

        assert set(root.node_snapshots()) == {"relay7:shard"}
        flat = merge_node_snapshots({
            f"client{cid}": m.registry.snapshot()
            for cid, m in members.items()
        })
        merged = root.merged()
        assert merged["steps"]["value"] == flat["steps"]["value"] == 12.0
        assert merged["local_step_s"] == flat["local_step_s"]


# ---- live-fleet acceptance e2e ----------------------------------------------

def _run_fleet_and_compare(tmp_path, pacing, n_clients=3, steps=4,
                           drive_push=False, expect_total=True,
                           fault_injector=None):
    """Run a simulated federation with telemetry-shipping clients and
    assert the server's live fleet-merged ``local_step_s`` equals the
    offline merge of the clients' own JSONL snapshots bucket-for-bucket
    (the 'live and post-hoc views can never disagree' contract)."""
    loggers = {
        cid: MetricsLogger(
            path=str(tmp_path / f"client{cid}.jsonl"),
            node=f"client{cid}", validate=True,
        )
        for cid in range(1, n_clients + 1)
    }
    server_m = MetricsLogger(validate=True, node="server")
    server, servicers, template = make_sim_fleet(
        n_clients, steps=steps, pacing_policy=pacing, max_iters=steps + 2,
        save_dir=str(tmp_path / "srv"), checkpoint_every=0,
        journal_every=0, metrics=server_m,
        client_metrics=lambda cid: loggers[cid],
        fault_injector=fault_injector,
    )
    try:
        if drive_push:
            seqs = dict.fromkeys(servicers, 0)
            while not server.training_done.is_set():
                for cid, servicer in servicers.items():
                    if servicer.finished:
                        continue
                    seqs[cid] += 1
                    update = servicer.build_update(template, seq=seqs[cid])
                    agg = server.PushUpdate(update, None)
                    servicer.apply(agg)
                    # a stub-level retry replays the identical request:
                    # seq dedup must keep the telemetry single-counted
                    server.PushUpdate(update, None)
        assert server.wait_done(timeout=120)
    finally:
        server.stop()

    fleet_nodes = server.fleet.node_snapshots()
    for cid in servicers:
        assert f"client{cid}" in fleet_nodes, (
            f"client{cid} never reached the fleet view: "
            f"{sorted(fleet_nodes)}"
        )
    live = server.fleet.merged()["local_step_s"]

    # Offline ground truth: each client dumps its cumulative registry to
    # its own JSONL; summarize-style per-node last-snapshot merge.
    per_node = {}
    for cid, m in loggers.items():
        m.snapshot_registry()
        m.close()
        records = read_metrics(str(tmp_path / f"client{cid}.jsonl"))
        snaps = [r for r in records if r["event"] == "metrics_snapshot"]
        per_node[f"client{cid}"] = snaps[-1]["metrics"]
    offline = merge_node_snapshots(per_node)["local_step_s"]

    assert live["edges"] == offline["edges"]
    assert live["counts"] == offline["counts"], (
        f"live fleet merge diverged from offline JSONL merge under "
        f"{pacing}: {live['counts']} != {offline['counts']}"
    )
    if expect_total:
        assert live["count"] == offline["count"] == n_clients * steps
    else:
        # cohort rotation polls clients unevenly before max_iters ends
        # the run — the exactness contract is live == offline, not a
        # fixed population total
        assert live["count"] == offline["count"] > 0
    assert live["sum"] == pytest.approx(offline["sum"])
    assert (live["min"], live["max"]) == (offline["min"], offline["max"])
    # the duplicate-push replays were deduplicated, never double-ingested
    if drive_push:
        assert server_m.registry.counter("rpcs_deduplicated").value > 0


class TestLiveFleetE2E:
    def test_sync_pacing_live_merge_equals_offline_merge(self, tmp_path):
        _run_fleet_and_compare(tmp_path, "sync")

    def test_cohort_pacing_live_merge_equals_offline_merge(self, tmp_path):
        # cohort:2 polls a rotating subset per round, so reports arrive
        # piecemeal across rounds — the cumulative-snapshot shipping must
        # still converge to the exact totals by the final round
        _run_fleet_and_compare(tmp_path, "cohort:2", steps=4,
                               expect_total=False)

    def test_push_pacing_with_replays_live_merge_equals_offline(
        self, tmp_path
    ):
        _run_fleet_and_compare(tmp_path, "push:2", drive_push=True)

    def test_partition_persona_loses_polls_not_training_or_exactness(
        self, tmp_path
    ):
        """Chaos persona: a client partitioned for a few polls (scripted
        UNAVAILABLE before the wire) must not perturb the round loop —
        the run still completes — and the fleet view must stay EXACTLY
        consistent with the clients' own JSONL: a failed poll never
        executed the step, so no observation can go missing or double."""
        from gfedntm_tpu.federation.resilience import FaultInjector

        injector = FaultInjector(seed=0)
        injector.script("TrainStep", kind="error", times=2,
                        peer="client2")
        _run_fleet_and_compare(
            tmp_path, "sync", steps=4, expect_total=False,
            fault_injector=injector,
        )
        assert injector.fired, "the partition persona never fired"

    def test_status_fleet_section_reports_population(self, tmp_path):
        loggers = {
            cid: MetricsLogger(node=f"client{cid}") for cid in (1, 2)
        }
        server_m = MetricsLogger(validate=True, node="server")
        server, servicers, template = make_sim_fleet(
            2, steps=3, pacing_policy="sync", max_iters=5,
            save_dir=str(tmp_path), checkpoint_every=0, journal_every=0,
            metrics=server_m, client_metrics=lambda cid: loggers[cid],
        )
        try:
            assert server.wait_done(timeout=120)
        finally:
            server.stop()
        status = server._status()
        fleet = status["fleet"]
        # server's own registry plus both clients
        assert fleet["nodes"] == 3
        assert fleet["reports_invalid"] == 0.0
        assert fleet["reports_dropped"] == 0.0
        assert fleet["alerts_firing"] is None  # no SLO specs configured
