"""Serving-plane suite (tier-1): the hot-swappable topic-inference
service (README "Serving").

Covers the ISSUE 13 satellites + acceptance flow: model-source
prefer-newer loading, encoder-only inference parity (deterministic,
batch-size invariant under bucketed padding, matches the training-path
posterior mean for AVITM and CTM), the quality-gated swap, the
coalescing batcher, the gRPC/HTTP front doors with ``/ready``
readiness, the BENCH_SERVE schema, and one end-to-end federation that
journals rounds while a serving plane hot-swaps through published
models under live closed-loop load with zero failed requests.
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest
from flax.traverse_util import flatten_dict

from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.federation.server import (
    FederatedServer,
    build_template_model,
)
from gfedntm_tpu.models.networks import DecoderNetwork
from gfedntm_tpu.serving import (
    Batcher,
    ClosedLoopLoadGen,
    ModelSource,
    ServingEngine,
    ServingPlane,
    default_buckets,
    make_infer_stub,
)
from gfedntm_tpu.train.checkpoint import (
    FederationCheckpointer,
    RoundJournal,
)
from gfedntm_tpu.utils.observability import MetricsLogger

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir,
                 "scripts"),
)
import bench_schema  # noqa: E402

MODEL_KWARGS = dict(
    n_components=3, hidden_sizes=(8,), batch_size=8, num_epochs=2, seed=0,
)
VOCAB = tuple(f"tok{i:02d}" for i in range(30))


def _flat_average(family="avitm", vocab=VOCAB, kwargs=MODEL_KWARGS,
                  scale=1.0):
    model = build_template_model(family, len(vocab), dict(kwargs))
    flat = flatten_dict(
        {"params": model.params, "batch_stats": model.batch_stats}, sep="/"
    )
    return {k: np.asarray(v) * scale for k, v in flat.items()}


def _extra(family="avitm", kwargs=MODEL_KWARGS, quality=None):
    extra = {"family": family, "model_kwargs": dict(kwargs)}
    if quality is not None:
        extra["quality"] = quality
    return extra


def _journal_round(tmp_path, round_idx, quality=None, scale=1.0):
    j = RoundJournal(os.path.join(str(tmp_path), "checkpoints"))
    j.record(
        round_idx, _flat_average(scale=scale), [], vocab=list(VOCAB),
        extra=_extra(quality=quality),
    )
    return j


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---- model source (journal/checkpoint prefer-newer) -------------------------

class TestModelSource:
    def test_empty_dir_has_nothing_and_reader_creates_nothing(self, tmp_path):
        src = ModelSource(str(tmp_path))
        assert src.peek() is None
        assert src.load() is None
        # a pure READER: a typo'd --save_dir must not get a store
        # planted into it by the watcher
        assert not os.path.exists(os.path.join(str(tmp_path), "checkpoints"))

    def test_journal_round_loads(self, tmp_path):
        _journal_round(tmp_path, 5)
        src = ModelSource(str(tmp_path))
        assert src.peek() == (5, "journal")
        pub = src.load()
        assert pub.round == 5 and pub.source == "journal"
        assert pub.vocab == VOCAB and pub.family == "avitm"
        assert pub.model_kwargs["n_components"] == 3
        assert "params/beta" in pub.average

    def test_checkpoint_round_loads_on_model_round_scale(self, tmp_path):
        """The checkpoint sidecar's `round` is the RESUME round (model
        round + 1) — the source normalizes it onto the journal's model-
        round scale so replies/gauges/publish ordering never mix the
        two."""
        ckpt = FederationCheckpointer(
            os.path.join(str(tmp_path), "checkpoints")
        )
        ckpt.save_round(
            7, _flat_average(), [], vocab=list(VOCAB), extra=_extra(),
        )
        src = ModelSource(str(tmp_path))
        assert src.peek() == (6, "checkpoint")
        pub = src.load()
        assert pub.round == 6 and pub.source == "checkpoint"
        assert set(pub.average) == set(_flat_average())

    def test_prefer_newer_journal_over_stale_checkpoint(self, tmp_path):
        ckpt = FederationCheckpointer(
            os.path.join(str(tmp_path), "checkpoints")
        )
        ckpt.save_round(3, _flat_average(), [], vocab=list(VOCAB),
                        extra=_extra())
        _journal_round(tmp_path, 9)
        src = ModelSource(str(tmp_path))
        assert src.peek() == (9, "journal")

    def test_prefer_newer_checkpoint_over_stale_journal(self, tmp_path):
        _journal_round(tmp_path, 2)
        ckpt = FederationCheckpointer(
            os.path.join(str(tmp_path), "checkpoints")
        )
        ckpt.save_round(8, _flat_average(), [], vocab=list(VOCAB),
                        extra=_extra())
        src = ModelSource(str(tmp_path))
        assert src.peek() == (7, "checkpoint")
        assert src.load().round == 7

    def test_journal_equal_to_checkpoint_model_round_wins(self, tmp_path):
        """Checkpoint resume-round C and journal round C-1 label the SAME
        state; the journal round C (one round newer) must win — before
        the scale normalization a checkpoint-sourced slot refused it."""
        ckpt = FederationCheckpointer(
            os.path.join(str(tmp_path), "checkpoints")
        )
        ckpt.save_round(8, _flat_average(), [], vocab=list(VOCAB),
                        extra=_extra())
        _journal_round(tmp_path, 8)
        src = ModelSource(str(tmp_path))
        assert src.peek() == (8, "journal")

    def test_finished_journal_still_serves(self, tmp_path):
        """A cleanly-finished federation's journal must not be served to
        auto-RECOVERY, but it is exactly what serving wants — the final
        model."""
        j = _journal_round(tmp_path, 6)
        j.mark_finished()
        src = ModelSource(str(tmp_path))
        assert src.peek() == (6, "journal")
        assert src.load().round == 6

    def test_corrupt_journal_degrades_quietly(self, tmp_path):
        """Halves-disagreement (the live mid-write race) degrades to the
        checkpoint with a retry counter, never an exception."""
        _journal_round(tmp_path, 4)
        meta_path = os.path.join(
            str(tmp_path), "checkpoints", RoundJournal.META_NAME
        )
        meta = json.load(open(meta_path))
        meta["round"] = 3  # stale JSON half
        with open(meta_path, "w") as fh:
            json.dump(meta, fh)
        m = MetricsLogger(validate=True)
        src = ModelSource(str(tmp_path), metrics=m)
        assert src.load() is None  # no checkpoint to degrade to
        assert m.registry.counter("serving_source_retries").value == 1

    def test_quality_record_rides_journal(self, tmp_path):
        _journal_round(
            tmp_path, 5,
            quality={"flagged": True, "unhealthy_streak": 2},
        )
        pub = ModelSource(str(tmp_path)).load()
        assert pub.flagged
        assert pub.quality["unhealthy_streak"] == 2


# ---- encoder-only inference parity (satellite) ------------------------------

class TestInferenceParity:
    def _engine_with(self, family="avitm", kwargs=MODEL_KWARGS):
        from gfedntm_tpu.serving.engine import PublishedModel

        pub = PublishedModel(
            round=1, source="journal", vocab=VOCAB, family=family,
            model_kwargs=dict(kwargs),
            average=_flat_average(family=family, kwargs=kwargs),
        )
        eng = ServingEngine(max_batch=8)
        assert eng.publish(pub)
        return eng

    def test_deterministic_no_sampling(self):
        eng = self._engine_with()
        x = np.random.default_rng(0).integers(
            0, 4, size=(5, len(VOCAB))
        ).astype(np.float32)
        t1, _ = eng.infer(x)
        t2, _ = eng.infer(x)
        np.testing.assert_array_equal(t1, t2)

    def test_batch_size_invariant_under_bucket_padding(self):
        """The same document yields the same theta whether it travels in
        a batch of 1 (bucket 1), 3 (bucket 4), or 8 (bucket 8) — padded
        rows cannot perturb real rows (eval-mode BN uses running stats)."""
        eng = self._engine_with()
        rng = np.random.default_rng(1)
        x = rng.integers(0, 4, size=(8, len(VOCAB))).astype(np.float32)
        full, _ = eng.infer(x)
        one, _ = eng.infer(x[:1])
        three, _ = eng.infer(x[:3])
        np.testing.assert_allclose(one, full[:1], atol=1e-6)
        np.testing.assert_allclose(three, full[:3], atol=1e-6)

    @pytest.mark.parametrize("family,kwargs", [
        ("avitm", MODEL_KWARGS),
        ("ctm", dict(MODEL_KWARGS, contextual_size=12,
                     inference_type="zeroshot")),
    ])
    def test_matches_training_path_posterior_mean(self, family, kwargs):
        """The serving theta IS softmax(posterior mean): compare against
        the training-path encoder (`encode_theta`, eval mode, zero
        noise) run through the module directly — for AVITM and CTM."""
        import jax.numpy as jnp

        eng = self._engine_with(family=family, kwargs=kwargs)
        slot = eng._slot
        rng = np.random.default_rng(2)
        x = rng.integers(0, 4, size=(6, len(VOCAB))).astype(np.float32)
        ctx = (
            rng.normal(size=(6, 12)).astype(np.float32)
            if family == "ctm" else None
        )
        theta, _ = eng.infer(x, ctx)
        out = slot.module.apply(
            {"params": slot.params, "batch_stats": slot.batch_stats},
            jnp.asarray(x),
            jnp.asarray(ctx) if ctx is not None else None,
            method=DecoderNetwork.encode_theta,
            train=False, noise=0.0,
        )
        np.testing.assert_allclose(
            theta, np.asarray(out.theta), atol=1e-5
        )
        # and softmax(mu) explicitly — no sampling anywhere in the path
        mu = np.asarray(out.posterior_mean, np.float64)
        e = np.exp(mu - mu.max(axis=1, keepdims=True))
        np.testing.assert_allclose(
            theta, e / e.sum(axis=1, keepdims=True), atol=1e-5
        )

    def test_get_theta_noise_zero_is_deterministic(self):
        """models/networks.get_theta with noise=0 needs no rng and equals
        the posterior-mean theta (the serving contract on the module
        itself)."""
        import jax.numpy as jnp

        eng = self._engine_with()
        slot = eng._slot
        x = jnp.asarray(
            np.random.default_rng(3).integers(
                0, 4, size=(4, len(VOCAB))
            ).astype(np.float32)
        )
        va = {"params": slot.params, "batch_stats": slot.batch_stats}
        t1 = slot.module.apply(
            va, x, method=DecoderNetwork.get_theta, noise=0.0
        )
        t2 = slot.module.apply(
            va, x, method=DecoderNetwork.get_theta, noise=0.0
        )
        np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))

    def test_chunking_above_max_batch(self):
        eng = self._engine_with()
        x = np.random.default_rng(4).integers(
            0, 4, size=(19, len(VOCAB))
        ).astype(np.float32)
        theta, _ = eng.infer(x)
        assert theta.shape == (19, 3)
        one, _ = eng.infer(x[17:18])
        np.testing.assert_allclose(one[0], theta[17], atol=1e-6)

    def test_vocab_width_mismatch_is_loud(self):
        eng = self._engine_with()
        with pytest.raises(ValueError, match="vocab width"):
            eng.infer(np.zeros((2, 7), np.float32))

    def test_default_buckets(self):
        assert default_buckets(64) == (1, 2, 4, 8, 16, 32, 64)
        assert default_buckets(6) == (1, 2, 4, 6)
        assert default_buckets(1) == (1,)


# ---- quality-gated hot swap (satellite) -------------------------------------

class TestQualityGatedSwap:
    def test_flagged_round_never_swaps_in(self, tmp_path):
        """A coherence-guard-flagged round is refused: the plane keeps
        the last good model and emits the counter + event."""
        m = MetricsLogger(validate=True)
        _journal_round(tmp_path, 5)
        src = ModelSource(str(tmp_path))
        eng = ServingEngine(max_batch=4, metrics=m)
        assert eng.publish(src.load())
        assert eng.model_round == 5

        _journal_round(
            tmp_path, 6,
            quality={"flagged": True, "unhealthy_streak": 2, "npmi": -0.4},
        )
        assert eng.publish(src.load()) is False
        assert eng.model_round == 5  # last good model keeps serving
        assert m.registry.counter("serving_swaps_refused").value == 1
        (ev,) = m.events("serve_swap_refused")
        assert ev["round"] == 6 and ev["reason"] == "coherence_flagged"
        assert ev["kept_round"] == 5

        # the NEXT healthy round swaps normally
        _journal_round(
            tmp_path, 7,
            quality={"flagged": False, "unhealthy_streak": 0},
        )
        assert eng.publish(src.load())
        assert eng.model_round == 7
        (swap,) = m.events("serve_model_swapped")
        assert swap["round"] == 7 and swap["prev_round"] == 5

    def test_gate_off_swaps_flagged(self, tmp_path):
        _journal_round(tmp_path, 5)
        src = ModelSource(str(tmp_path))
        eng = ServingEngine(max_batch=4, quality_gate=False)
        assert eng.publish(src.load())
        _journal_round(tmp_path, 6, quality={"flagged": True})
        assert eng.publish(src.load())
        assert eng.model_round == 6

    def test_stale_round_is_not_a_swap(self, tmp_path):
        _journal_round(tmp_path, 5)
        pub = ModelSource(str(tmp_path)).load()
        eng = ServingEngine(max_batch=4)
        assert eng.publish(pub)
        assert eng.publish(pub) is False  # same round again

    def test_swap_invisible_to_inflight_requests(self, tmp_path):
        """A slot reference taken before a swap keeps answering — the
        atomicity contract at the engine level."""
        _journal_round(tmp_path, 5)
        src = ModelSource(str(tmp_path))
        eng = ServingEngine(max_batch=4)
        eng.publish(src.load())
        slot_before = eng._slot
        _journal_round(tmp_path, 6, scale=0.5)
        eng.publish(src.load())
        assert eng._slot is not slot_before  # swapped
        # the old slot still computes (buffers never torn down under it)
        x = np.ones((2, len(VOCAB)), np.float32)
        theta = eng._infer_bucket(slot_before, x, None)
        assert np.isfinite(theta).all()


# ---- coalescing batcher -----------------------------------------------------

class TestBatcher:
    def test_concurrent_submits_coalesce_and_resolve(self, tmp_path):
        m = MetricsLogger(validate=True)
        _journal_round(tmp_path, 1)
        eng = ServingEngine(max_batch=16, metrics=m)
        eng.publish(ModelSource(str(tmp_path)).load())
        b = Batcher(eng, linger_s=0.005, metrics=m)
        b.start()
        try:
            rng = np.random.default_rng(0)
            xs = [
                rng.integers(0, 4, size=(2, len(VOCAB))).astype(np.float32)
                for _ in range(12)
            ]
            futs = [b.submit(x) for x in xs]
            for x, f in zip(xs, futs):
                theta, round_idx = f.result(timeout=30)
                assert theta.shape == (2, 3) and round_idx == 1
                expect, _ = eng.infer(x)
                np.testing.assert_allclose(theta, expect, atol=1e-6)
        finally:
            b.stop()
        assert m.registry.counter("serving_requests").value == 12
        assert m.registry.counter("serving_docs").value >= 24

    def test_oversize_request_rejected(self, tmp_path):
        _journal_round(tmp_path, 1)
        eng = ServingEngine(max_batch=4)
        eng.publish(ModelSource(str(tmp_path)).load())
        b = Batcher(eng)
        with pytest.raises(ValueError, match="max_batch"):
            b.submit(np.zeros((5, len(VOCAB)), np.float32))

    def test_wrong_width_request_rejected_alone(self, tmp_path):
        """A wrong-vocab-width request fails at submit — coalesced into a
        micro-batch it would poison every co-batched request's future."""
        _journal_round(tmp_path, 1)
        eng = ServingEngine(max_batch=8)
        eng.publish(ModelSource(str(tmp_path)).load())
        b = Batcher(eng, linger_s=0.01)
        b.start()
        try:
            with pytest.raises(ValueError, match="vocab width"):
                b.submit(np.zeros((2, 7), np.float32))
            # a valid request right after still succeeds
            theta, _ = b.submit(
                np.ones((2, len(VOCAB)), np.float32)
            ).result(timeout=30)
            assert theta.shape == (2, 3)
        finally:
            b.stop()

    def test_stop_fails_pending_loudly(self, tmp_path):
        _journal_round(tmp_path, 1)
        eng = ServingEngine(max_batch=4)
        eng.publish(ModelSource(str(tmp_path)).load())
        b = Batcher(eng)  # never started: submissions just queue
        fut = b.submit(np.zeros((1, len(VOCAB)), np.float32))
        b.stop()
        with pytest.raises(RuntimeError, match="stopped"):
            fut.result(timeout=5)


class _SlowEngine:
    """Stub engine with a fixed per-batch service time: makes offered
    load > capacity deterministic without tuning real JIT timings."""

    max_batch = 16
    vocab = None

    def __init__(self, service_s=0.03, n_components=3):
        self.service_s = service_s
        self.n_components = n_components

    def infer(self, x):
        time.sleep(self.service_s)
        return (
            np.full((x.shape[0], self.n_components), 1.0 / 3, np.float32),
            5,
        )


class TestLoadShedding:
    """ISSUE 14 satellite: the pending queue is bounded by
    --serve_max_queue (docs); overload sheds the ARRIVING request alone
    with RESOURCE_EXHAUSTED/429 while accepted requests never fail."""

    def test_overload_sheds_bounded_queue_zero_accepted_failures(self):
        from gfedntm_tpu.serving import QueueFullError

        m = MetricsLogger(validate=True)
        b = Batcher(
            _SlowEngine(service_s=0.02), linger_s=0.0, metrics=m,
            max_queue=8,
        )
        b.start()
        sheds = 0
        latencies = []
        failures = []
        lock = threading.Lock()

        def worker():
            nonlocal sheds
            # Closed loop: one request in flight per worker; a shed is
            # counted and immediately retried with fresh pressure.
            for _ in range(12):
                t0 = time.perf_counter()
                try:
                    fut = b.submit(np.ones((2, 10), np.float32))
                except QueueFullError:
                    with lock:
                        sheds += 1
                    continue
                try:
                    theta, rnd = fut.result(timeout=30)
                    assert theta.shape == (2, 3) and rnd == 5
                    with lock:
                        latencies.append(time.perf_counter() - t0)
                except Exception as err:  # pragma: no cover - the bug
                    with lock:
                        failures.append(err)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        b.stop()

        assert not failures, failures
        assert latencies, "no requests were accepted at all"
        assert sheds > 0, "overload never shed — the bound is inert"
        # zero accepted-request failures + shed accounting line up
        assert m.registry.counter("serving_requests_shed").value == sheds
        shed_events = m.events("serve_shed")
        assert len(shed_events) == sheds
        # queue depth stayed bounded: every shed observed <= max_queue
        # pending docs, and the live gauge never exceeded the bound
        assert all(ev["queued"] <= 8 for ev in shed_events)
        assert m.registry.get("serving_queue_depth").value <= 8
        # p99 bounded: a bounded queue bounds the wait (8 queued docs +
        # one in-flight batch at 30 ms service time is well under 2 s)
        assert float(np.percentile(latencies, 99)) < 2.0

    def test_grpc_infer_maps_shed_to_resource_exhausted(self):
        import grpc

        from gfedntm_tpu.federation import codec
        from gfedntm_tpu.federation.protos import federated_pb2 as pb
        from gfedntm_tpu.serving import InferenceServicer, QueueFullError

        class _FullBatcher:
            engine = _SlowEngine()

            def submit(self, x):
                raise QueueFullError("serving queue full")

        class _Abort(Exception):
            pass

        class _Ctx:
            code = None

            def abort(self, code, details):
                self.code = code
                raise _Abort(details)

        servicer = InferenceServicer(_FullBatcher())
        req = pb.InferRequest(request_id=1)
        req.bow.tensors.append(
            codec.array_to_record("bow", np.ones((1, 4), np.float32))
        )
        ctx = _Ctx()
        with pytest.raises(_Abort, match="queue full"):
            servicer.Infer(req, ctx)
        assert ctx.code is grpc.StatusCode.RESOURCE_EXHAUSTED

    def test_http_infer_maps_shed_to_429(self, tmp_path):
        from gfedntm_tpu.serving import QueueFullError

        plane = ServingPlane(str(tmp_path), max_queue=4)

        class _FullBatcher:
            engine = plane.engine
            max_queue = 4

            def submit(self, x):
                raise QueueFullError("serving queue full (4/4)")

        plane.batcher = _FullBatcher()
        status, ctype, body = plane._http_infer(
            json.dumps({"bow": [[1, 0, 2]]}).encode(), ""
        )
        assert status == 429
        assert "queue full" in json.loads(body)["error"]

    def test_oversized_request_on_idle_queue_is_served_not_shed(self):
        """A request wider than max_queue (but within max_batch) must be
        admitted when the queue is idle — shedding it with 'retry later'
        would be a permanently unservable retry loop."""
        b = Batcher(_SlowEngine(service_s=0.0), linger_s=0.0, max_queue=4)
        b.start()
        try:
            theta, rnd = b.submit(
                np.ones((8, 10), np.float32)
            ).result(timeout=30)
            assert theta.shape == (8, 3) and rnd == 5
        finally:
            b.stop()

    def test_max_queue_validation_and_cli_flag(self):
        with pytest.raises(ValueError, match="max_queue"):
            Batcher(_SlowEngine(), max_queue=-1)
        from gfedntm_tpu.cli import build_parser

        args = build_parser().parse_args(["--serve_max_queue", "256"])
        assert args.serve_max_queue == 256
        assert build_parser().parse_args([]).serve_max_queue == 0


# ---- front doors: /ready, HTTP /infer, gRPC Infer ---------------------------

def _http(url, data=None, expect_error=False):
    try:
        req = urllib.request.Request(
            url, data=data,
            headers={"Content-Type": "application/json"} if data else {},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        if not expect_error:
            raise
        return err.code, err.read()


class TestFrontDoors:
    def test_ready_distinct_from_healthz_and_http_infer(self, tmp_path):
        m = MetricsLogger(validate=True)
        plane = ServingPlane(
            str(tmp_path), max_batch=8, poll_s=0.1, metrics=m, ops_port=0,
        )
        plane.start("[::]:0")
        try:
            base = f"http://127.0.0.1:{plane.ops_actual_port}"
            # alive but NOT ready: nothing published yet
            assert _http(f"{base}/healthz")[0] == 200
            code, body = _http(f"{base}/ready", expect_error=True)
            assert code == 503 and b"not ready" in body
            # publish round 2 -> watcher picks it up -> ready flips
            _journal_round(tmp_path, 2)
            deadline = time.time() + 30
            while not plane.engine.ready and time.time() < deadline:
                time.sleep(0.05)
            assert plane.engine.ready
            assert _http(f"{base}/ready")[0] == 200

            # HTTP /infer with raw text docs (tokenized against the
            # serving model's vocabulary)
            code, body = _http(
                f"{base}/infer",
                json.dumps({"docs": ["tok01 tok02 tok01", "tok05"]}).encode(),
            )
            assert code == 200
            out = json.loads(body)
            theta = np.asarray(out["theta"])
            assert theta.shape == (2, 3) and out["model_round"] == 2
            np.testing.assert_allclose(theta.sum(1), 1.0, atol=1e-3)

            # dense bow rows work too
            code, body = _http(
                f"{base}/infer",
                json.dumps(
                    {"bow": np.ones((1, len(VOCAB))).tolist()}
                ).encode(),
            )
            assert code == 200

            # bad request -> 400 + serve_error event
            code, body = _http(
                f"{base}/infer", json.dumps({"nope": 1}).encode(),
                expect_error=True,
            )
            assert code == 400
            assert m.events("serve_error")

            # /status carries the serving view
            code, body = _http(f"{base}/status")
            status = json.loads(body)
            assert status["serving"]["ready"] is True
            assert status["serving"]["model_round"] == 2
            assert status["serving"]["requests"] >= 2
        finally:
            plane.stop()

    def test_grpc_infer_roundtrip(self, tmp_path):
        _journal_round(tmp_path, 3)
        plane = ServingPlane(str(tmp_path), max_batch=8, poll_s=0.1)
        plane.start("[::]:0")
        try:
            deadline = time.time() + 30
            while not plane.engine.ready and time.time() < deadline:
                time.sleep(0.05)
            infer = make_infer_stub(f"localhost:{plane.bound_port}")
            x = np.random.default_rng(0).integers(
                0, 4, size=(4, len(VOCAB))
            ).astype(np.float32)
            theta, model_round = infer(x, request_id=11)
            assert theta.shape == (4, 3) and model_round == 3
            expect, _ = plane.engine.infer(x)
            np.testing.assert_allclose(theta, expect, atol=1e-6)
            infer.channel.close()
        finally:
            plane.stop()


# ---- journal self-description (server side) ---------------------------------

class TestJournalSelfDescription:
    def test_state_extra_carries_model_kwargs_and_quality(self):
        server = FederatedServer(
            min_clients=1, family="avitm",
            model_kwargs=dict(MODEL_KWARGS), quality_every=1,
        )
        extra = server._state_extra()
        assert extra["family"] == "avitm"
        assert extra["model_kwargs"]["n_components"] == 3
        assert "quality" not in extra  # monitor not constructed yet

        class FakeMonitor:
            def status(self):
                return {
                    "unhealthy_streak": 2,
                    "last": {"npmi": -0.3, "round": 12},
                }

        server._quality_mon = FakeMonitor()
        extra = server._state_extra()
        assert extra["quality"]["flagged"] is True
        assert extra["quality"]["unhealthy_streak"] == 2
        assert extra["quality"]["npmi"] == -0.3
        server._quality_mon = None

    def test_extra_is_json_able(self):
        server = FederatedServer(
            min_clients=1, family="avitm", model_kwargs=dict(MODEL_KWARGS),
        )
        json.dumps(server._state_extra())


# ---- BENCH_SERVE schema -----------------------------------------------------

class TestServeBenchSchema:
    def _artifact(self):
        return {
            "bench": "serve", "rev": "r01", "backend": "cpu",
            "target_p99_ms": 250.0, "sustained_docs_per_s": 100.0,
            "qps": 10.0, "p50_ms": 5.0, "p99_ms": 50.0, "swaps": 3,
            "failures": 0, "series": [], "acceptance": {},
        }

    def test_valid_artifact_passes(self):
        assert bench_schema.validate(self._artifact(), "serve_bench") == []

    def test_missing_field_fails(self):
        bad = self._artifact()
        del bad["swaps"]
        problems = bench_schema.validate(bad, "serve_bench")
        assert any("swaps" in p for p in problems)


# ---- CLI surface ------------------------------------------------------------

class TestServeCli:
    def test_parser_accepts_serve_role(self):
        from gfedntm_tpu.cli import build_parser

        args = build_parser().parse_args(
            ["--role", "serve", "--save_dir", "out",
             "--serve_max_batch", "32", "--serve_poll", "0.5",
             "--serve_duration", "3", "--no_quality_gate"]
        )
        assert args.role == "serve"
        assert args.serve_max_batch == 32
        assert args.serve_poll == 0.5
        assert args.serve_duration == 3.0
        assert args.no_quality_gate is True

    def test_serve_defaults(self):
        from gfedntm_tpu.cli import build_parser

        args = build_parser().parse_args(["--role", "serve"])
        assert args.serve_max_batch == 64
        assert args.serve_linger_ms == 2.0
        assert args.serve_duration == 0.0
        assert args.no_quality_gate is False


def test_loadgen_min_rounds_extends_past_duration():
    """With ``min_rounds`` set, the run extends past the ``duration_s``
    floor until the load has observed that many distinct model rounds —
    and stops there, not at ``max_duration_s``."""
    t0 = time.perf_counter()
    lock = threading.Lock()

    def infer(x):
        # The "model" advances a round every 0.3 s of wall clock, so a
        # 0.2 s floor can only ever see round 0 — reaching 3 distinct
        # rounds REQUIRES the condition-driven extension.
        with lock:
            rnd = int((time.perf_counter() - t0) / 0.3)
        return np.full((x.shape[0], 3), 1 / 3, np.float32), rnd

    gen = ClosedLoopLoadGen(
        infer, lambda w, s: np.zeros((2, 5), np.float32),
        concurrency=2, duration_s=0.2, min_rounds=3, max_duration_s=10.0,
    )
    summary = gen.run()
    assert summary["swaps_observed"] >= 2, summary["model_rounds_seen"]
    assert 0.2 < summary["duration_s"] < 5.0
    assert summary["failures"] == 0

    with pytest.raises(ValueError):
        ClosedLoopLoadGen(
            infer, lambda w, s: None, duration_s=0.1, min_rounds=0,
        )


# ---- end to end: live federation + hot-swapping serve + closed loop ---------

def _run_clients(clients):
    threads = [
        threading.Thread(target=c.run, daemon=True,
                         name=f"client{c.client_id}")
        for c in clients
    ]
    for t in threads:
        t.start()
    return threads


@pytest.mark.chaos
def test_e2e_hot_swap_under_live_load(tmp_path):
    """The ISSUE 13 acceptance flow, in-process: a 2-client federation
    journals rounds while a serving plane polls the same save_dir and
    hot-swaps through >= 2 published models UNDER a live closed-loop
    load — zero failed in-flight requests, swap/latency/QPS telemetry in
    the JSONL stream, and the load generator's summary carries the
    BENCH_SERVE building blocks."""
    from gfedntm_tpu.federation.client import Client

    rng = np.random.default_rng(0)
    words = [f"tok{i:02d}" for i in range(45)]
    corpora = [
        RawCorpus(documents=[
            " ".join(rng.choice(words, size=12)) for _ in range(40)
        ])
        for _ in range(2)
    ]
    port = _free_port()
    srv_dir = str(tmp_path / "fed")
    # Enough epochs (~200 rounds) that the federation outlasts the load
    # window even on a fast box — the plane must still be swapping while
    # the load generator watches for its 2 swaps.
    kwargs = dict(MODEL_KWARGS, num_epochs=40)
    ms = MetricsLogger(str(tmp_path / "server.jsonl"), validate=True)
    server = FederatedServer(
        min_clients=2, family="avitm", model_kwargs=kwargs, max_iters=300,
        save_dir=srv_dir, metrics=ms, checkpoint_every=0, journal_every=1,
    )
    server.start(f"[::]:{port}")
    mc = MetricsLogger(validate=True)
    clients = [
        Client(client_id=c + 1, corpus=corpus,
               server_address=f"localhost:{port}", max_features=45,
               save_dir=str(tmp_path / f"c{c + 1}"), metrics=mc)
        for c, corpus in enumerate(corpora)
    ]
    threads = _run_clients(clients)

    mserve = MetricsLogger(
        str(tmp_path / "serve" / "metrics.jsonl"), validate=True,
        keep_records=True,
    )
    plane = ServingPlane(
        srv_dir, max_batch=32, poll_s=0.1, metrics=mserve, ops_port=0,
    )
    plane.start("[::]:0")
    try:
        deadline = time.time() + 120
        while not plane.engine.ready and time.time() < deadline:
            time.sleep(0.1)
        assert plane.engine.ready, "no model ever published"
        vocab_size = len(plane.engine.vocab)

        infer = make_infer_stub(f"localhost:{plane.bound_port}")
        # per-worker generators: np.random.Generator is not thread-safe
        batch_rngs = [np.random.default_rng(7 + i) for i in range(4)]

        def make_batch(worker, seq):
            return batch_rngs[worker].integers(
                0, 3, size=(4, vocab_size)
            ).astype(np.float32)

        # Condition-driven window: at least 6 s of load, extended until
        # the responses have ridden through >= 3 distinct model rounds
        # (>= 2 swaps) or the 45 s cap — a fixed window races the
        # trainer's round rate against the swap cost, and both scale
        # with machine load.
        gen = ClosedLoopLoadGen(
            infer, make_batch, concurrency=4, duration_s=6.0,
            metrics=mserve, min_rounds=3, max_duration_s=45.0,
        )
        summary = gen.run()
        infer.channel.close()
    finally:
        plane.stop()
        server.stop()
        for c in clients:
            c.shutdown()
        for t in threads:
            t.join(timeout=30)
        ms.close()
        mc.close()
        mserve.close()

    # zero failed in-flight requests across every live swap
    assert summary["failures"] == 0, summary["failure_samples"]
    assert summary["requests"] > 0
    # the load itself rode through >= 2 model swaps (>= 3 distinct rounds)
    assert summary["swaps_observed"] >= 2, summary["model_rounds_seen"]
    assert summary["docs_per_s"] > 0 and summary["p99_ms"] is not None

    # telemetry: swap audit + latency series reproducible from JSONL alone
    reg = mserve.registry
    assert reg.counter("serving_swaps").value >= 2
    assert reg.get("serve_latency_s").count == summary["requests"]
    swaps = mserve.events("serve_model_swapped")
    assert len(swaps) >= 2
    rounds = [ev["round"] for ev in swaps]
    assert rounds == sorted(rounds)  # monotone swap trail
    windows = mserve.events("serve_load_window")
    assert windows and sum(w["docs"] for w in windows) == summary["docs"]
    # /status-served serving view stayed coherent
    status = plane._status()
    assert status["serving"]["swaps"] >= 2
    assert status["serving"]["errors"] == 0
