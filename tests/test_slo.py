"""SLO engine + alerting tests (ISSUE 16): spec validation, the
pending→firing→resolved state machine (dwell, silent pending clears,
no-data hold), windowed/rate measurement over cumulative snapshots, the
offline stream evaluator the ``slo`` CLI gate runs, alert sections in
summarize/report, the live ``/alerts`` endpoint, and the acceptance e2e:
a real load-shed storm on the serving Batcher drives a shed-rate SLO
through the full alert lifecycle while a no-storm twin stays green.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from gfedntm_tpu.cli import main as cli_main
from gfedntm_tpu.utils.observability import (
    MetricsLogger,
    OpsServer,
    format_report,
    summarize_metrics,
)
from gfedntm_tpu.utils.slo import (
    SLOEngine,
    SLOSpec,
    evaluate_stream,
    load_slo_specs,
)


def _spec(**over):
    base = dict(name="errs", metric="serving_errors", agg="value",
                op="<=", threshold=0.0)
    base.update(over)
    return base


# ---- spec validation ---------------------------------------------------------

class TestSLOSpec:
    def test_valid_spec_and_objective_text(self):
        spec = SLOSpec.from_dict(_spec(
            name="p99", metric="serve_latency_s", agg="p99", op="<=",
            threshold=0.25, window_s=60, for_s=10,
        ))
        assert spec.objective() == "p99(serve_latency_s) over 60s <= 0.25"

    @pytest.mark.parametrize("bad", [
        _spec(agg="p42"),
        _spec(op="=="),
        _spec(agg="rate"),  # rate needs window_s > 0
        _spec(name=""),
        _spec(typo=1),  # unknown key
        {"name": "x", "metric": "m"},  # missing op/threshold
    ])
    def test_invalid_specs_fail_loudly(self, bad):
        with pytest.raises(ValueError):
            SLOSpec.from_dict(bad)

    def test_duplicate_names_rejected_at_engine_build(self):
        with pytest.raises(ValueError, match="duplicate"):
            SLOEngine([_spec(), _spec()], snapshot_fn=dict)

    def test_load_specs_inline_file_and_wrapper(self, tmp_path):
        inline = json.dumps([_spec()])
        assert load_slo_specs(inline)[0].name == "errs"
        wrapped = json.dumps({"slos": [_spec(name="a"), _spec(name="b")]})
        assert [s.name for s in load_slo_specs(wrapped)] == ["a", "b"]
        path = tmp_path / "slo.json"
        path.write_text(inline)
        assert load_slo_specs(str(path))[0].metric == "serving_errors"
        with pytest.raises(ValueError):
            load_slo_specs("not json at all")
        with pytest.raises(ValueError):
            load_slo_specs(json.dumps({"no_slos_key": True}) + "x")


# ---- state machine -----------------------------------------------------------

class TestAlertStateMachine:
    def test_full_lifecycle_with_dwell_and_events(self):
        m = MetricsLogger(validate=True, node="server")
        snap = {"serving_errors": {"type": "counter", "value": 0.0}}
        engine = SLOEngine(
            [_spec(for_s=5.0)], snapshot_fn=lambda: snap, metrics=m,
        )
        assert engine.evaluate(now=100.0) == []
        # violation enters pending, does NOT fire inside the dwell
        snap["serving_errors"]["value"] = 3.0
        trs = engine.evaluate(now=101.0)
        assert trs == [{"alert": "errs", "from": "ok", "to": "pending"}]
        assert engine.evaluate(now=103.0) == []  # still pending
        assert engine.ever_fired() == []
        # dwell elapsed → firing
        trs = engine.evaluate(now=106.5)
        assert trs == [{"alert": "errs", "from": "pending",
                        "to": "firing"}]
        assert engine.status()["firing"] == 1
        assert m.registry.gauge("slo_alerts_firing").value == 1.0
        # objective met again → resolved
        snap["serving_errors"]["value"] = 0.0
        trs = engine.evaluate(now=110.0)
        assert trs == [{"alert": "errs", "from": "firing",
                        "to": "resolved"}]
        assert engine.ever_fired() == ["errs"]
        # the JSONL trail carries the whole lifecycle
        assert len(m.events("alert_pending")) == 1
        firing = m.events("alert_firing")
        assert len(firing) == 1
        assert firing[0]["pending_s"] == pytest.approx(5.5)
        assert firing[0]["objective"] == "value(serving_errors) <= 0"
        assert len(m.events("alert_resolved")) == 1

    def test_short_violation_clears_pending_silently(self):
        m = MetricsLogger(validate=True, node="server")
        snap = {"serving_errors": {"type": "counter", "value": 0.0}}
        engine = SLOEngine(
            [_spec(for_s=10.0)], snapshot_fn=lambda: snap, metrics=m,
        )
        engine.evaluate(now=0.0)
        snap["serving_errors"]["value"] = 1.0
        engine.evaluate(now=1.0)
        snap["serving_errors"]["value"] = 0.0
        trs = engine.evaluate(now=2.0)
        assert trs == [{"alert": "errs", "from": "pending", "to": "ok"}]
        # pending is not an alert yet: no resolved event, nothing fired
        assert m.events("alert_resolved") == []
        assert engine.ever_fired() == []

    def test_no_data_holds_state_never_resolves(self):
        snap = {}
        engine = SLOEngine(
            [_spec(for_s=0.0)], snapshot_fn=lambda: dict(snap),
        )
        snap["serving_errors"] = {"type": "counter", "value": 2.0}
        engine.evaluate(now=0.0)
        assert engine.status()["alerts"][0]["state"] == "firing"
        # the metric disappears (crashed reporter): firing must HOLD —
        # silence is not success
        del snap["serving_errors"]
        assert engine.evaluate(now=10.0) == []
        assert engine.status()["alerts"][0]["state"] == "firing"

    def test_gauge_and_histogram_percentile_objectives(self):
        m = MetricsLogger(validate=True)
        h = m.registry.histogram("serve_latency_s")
        for v in [0.01] * 95 + [2.0] * 5:
            h.observe(v)
        m.registry.gauge("serving_queue_depth").set(3.0)
        engine = SLOEngine(
            [
                {"name": "p99", "metric": "serve_latency_s",
                 "agg": "p99", "op": "<=", "threshold": 0.25},
                {"name": "p50", "metric": "serve_latency_s",
                 "agg": "p50", "op": "<=", "threshold": 0.25},
                {"name": "queue", "metric": "serving_queue_depth",
                 "agg": "value", "op": "<", "threshold": 8},
            ],
            snapshot_fn=m.registry.snapshot,
        )
        engine.evaluate(now=0.0)
        states = {a["alert"]: a["state"]
                  for a in engine.status()["alerts"]}
        # the tail breaches, the median and the gauge hold
        assert states == {"p99": "firing", "p50": "ok", "queue": "ok"}

    def test_windowed_rate_fires_during_burn_and_resolves_after(self):
        m = MetricsLogger(validate=True)
        c = m.registry.counter("serving_requests_shed")
        engine = SLOEngine(
            [{"name": "shed-rate", "metric": "serving_requests_shed",
              "agg": "rate", "op": "<=", "threshold": 0.5,
              "window_s": 5.0}],
            snapshot_fn=m.registry.snapshot,
        )
        engine.evaluate(now=0.0)  # baseline
        c.inc(100)  # burn: 100 sheds in 2 s
        engine.evaluate(now=2.0)
        assert engine.status()["alerts"][0]["state"] == "firing"
        assert engine.status()["alerts"][0]["value"] == pytest.approx(50.0)
        # storm over: the counter is monotone, but the RATE over the
        # trailing window decays back under threshold → resolved
        engine.evaluate(now=8.0)
        engine.evaluate(now=14.0)
        assert engine.status()["alerts"][0]["state"] == "resolved"


# ---- offline stream evaluator (the `slo` CLI engine) ------------------------

class TestEvaluateStream:
    def _records(self, node, values, t0=1000.0):
        return [
            {"event": "metrics_snapshot", "time": t0 + i, "node": node,
             "metrics": {"steps": {"type": "counter",
                                   "value": float(v)}}}
            for i, v in enumerate(values)
        ]

    def test_violation_only_visible_in_fleet_merge(self):
        # each node stays under the threshold alone; only the exact
        # cross-node merge crosses it — the fleet view is load-bearing
        specs = [{"name": "total", "metric": "steps", "agg": "value",
                  "op": "<=", "threshold": 5.0}]
        nodes = {
            "client1": self._records("client1", [1, 2, 3]),
            "client2": self._records("client2", [1, 2, 3]),
        }
        engine = evaluate_stream(nodes, specs)
        assert engine.ever_fired() == ["total"]
        clean = evaluate_stream(
            {"client1": nodes["client1"]}, specs
        )
        assert clean.ever_fired() == []

    def test_non_snapshot_events_and_bad_times_ignored(self):
        records = self._records("server", [0, 0]) + [
            {"event": "round_started", "time": 1.0},
            {"event": "metrics_snapshot", "time": "garbage",
             "metrics": {}},
        ]
        engine = evaluate_stream({"server": records},
                                 [_spec(metric="steps", op="<=",
                                        threshold=10.0)])
        assert engine.ever_fired() == []


# ---- CLI gate ----------------------------------------------------------------

class TestSloCli:
    def _write_stream(self, path, values):
        with open(path, "w") as fh:
            for i, v in enumerate(values):
                fh.write(json.dumps({
                    "event": "metrics_snapshot", "time": 1000.0 + i,
                    "node": "server",
                    "metrics": {"serving_errors": {"type": "counter",
                                                   "value": float(v)}},
                }) + "\n")

    def test_exit_codes_and_json_out(self, tmp_path, capsys):
        spec_path = tmp_path / "slo.json"
        spec_path.write_text(json.dumps([_spec()]))
        good = tmp_path / "good.jsonl"
        bad = tmp_path / "bad.jsonl"
        self._write_stream(good, [0, 0, 0])
        self._write_stream(bad, [0, 4, 9])
        assert cli_main(["slo", "--slo", str(spec_path),
                         str(good)]) in (0, None)
        assert "SLO check passed" in capsys.readouterr().out
        out_json = tmp_path / "alerts.json"
        rc = cli_main(["slo", "--slo", str(spec_path), "--json",
                       str(out_json), str(bad)])
        assert rc == 1
        assert "FIRED" in capsys.readouterr().out
        status = json.loads(out_json.read_text())
        assert status["alerts"][0]["ever_fired"] is True

    def test_bad_and_empty_specs_are_usage_errors(self, tmp_path):
        stream = tmp_path / "m.jsonl"
        self._write_stream(stream, [0])
        with pytest.raises(SystemExit):
            cli_main(["slo", "--slo", "[{broken", str(stream)])
        with pytest.raises(SystemExit):
            cli_main(["slo", "--slo", "[]", str(stream)])


# ---- report rendering --------------------------------------------------------

class TestAlertReporting:
    def test_summarize_and_report_carry_alert_sections(self):
        m = MetricsLogger(validate=True, node="server")
        snap = {"serving_errors": {"type": "counter", "value": 0.0}}
        engine = SLOEngine([_spec(for_s=0.0)],
                           snapshot_fn=lambda: snap, metrics=m)
        engine.evaluate(now=0.0)
        snap["serving_errors"]["value"] = 2.0
        engine.evaluate(now=1.0)
        snap["serving_errors"]["value"] = 0.0
        engine.evaluate(now=2.0)
        s = summarize_metrics(m.records)
        assert s["alerts"]["errs"]["firing"] == 1
        assert s["alerts"]["errs"]["last_state"] == "resolved"
        text = format_report(s)
        assert "errs" in text and "resolved" in text

    def test_clean_run_report_has_no_alert_noise(self):
        m = MetricsLogger(validate=True, node="server")
        snap = {"serving_errors": {"type": "counter", "value": 0.0}}
        engine = SLOEngine([_spec()], snapshot_fn=lambda: snap,
                           metrics=m)
        engine.evaluate(now=0.0)
        s = summarize_metrics(m.records)
        assert s["alerts"] == {}


# ---- serving-plane acceptance e2e -------------------------------------------

class _SlowEngine:
    """Stub inference engine with a fixed service time (the
    test_serving.py load-shed pattern)."""

    max_batch = 16
    vocab = None

    def __init__(self, service_s=0.02):
        self.service_s = service_s

    def infer(self, x):
        import time as _time

        _time.sleep(self.service_s)
        return np.zeros((x.shape[0], 3), np.float32), 5


class TestServingAlertLifecycleE2E:
    def test_shed_storm_drives_alert_lifecycle_no_storm_twin_green(self):
        from gfedntm_tpu.serving import Batcher, QueueFullError

        m = MetricsLogger(validate=True, node="serve")
        # rate objectives need a window baseline: register the counter
        # up front so the pre-storm evaluation records shed=0 (a metric
        # born mid-window has no baseline and stays "no data")
        m.registry.counter("serving_requests_shed")
        engine = SLOEngine(
            [{"name": "shed-rate", "metric": "serving_requests_shed",
              "agg": "rate", "op": "<=", "threshold": 0.0,
              "window_s": 30.0, "for_s": 0.0}],
            snapshot_fn=m.registry.snapshot, metrics=m,
        )
        ops = OpsServer(registry=m.registry, alerts_fn=engine.status)
        port = ops.start()
        b = Batcher(_SlowEngine(), linger_s=0.0, metrics=m, max_queue=4)
        b.start()
        try:
            engine.evaluate()  # pre-storm baseline: green
            assert engine.status()["firing"] == 0

            sheds = 0
            lock = threading.Lock()

            def worker():
                nonlocal sheds
                for _ in range(10):
                    try:
                        fut = b.submit(np.ones((2, 10), np.float32))
                    except QueueFullError:
                        with lock:
                            sheds += 1
                        continue
                    fut.result(timeout=30)

            threads = [threading.Thread(target=worker)
                       for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert sheds > 0, "overload never shed — no storm to alert on"

            # induced degradation → pending → firing, live at /alerts
            engine.evaluate()
            url = f"http://127.0.0.1:{port}/alerts"
            with urllib.request.urlopen(url, timeout=10) as resp:
                live = json.loads(resp.read())
            assert live["firing"] == 1
            assert live["alerts"][0]["alert"] == "shed-rate"
            assert live["alerts"][0]["state"] == "firing"

            # storm over: the windowed rate decays → resolved (the
            # window baseline must age past the storm, so evaluate with
            # explicit post-window timestamps)
            import time as _time

            now = _time.time()
            engine.evaluate(now=now + 31.0)
            engine.evaluate(now=now + 62.0)
            assert engine.status()["alerts"][0]["state"] == "resolved"
            assert engine.ever_fired() == ["shed-rate"]
            assert len(m.events("alert_firing")) == 1
            assert len(m.events("alert_resolved")) == 1
        finally:
            b.stop()
            ops.stop()

        # the no-fault twin: same objective, no storm → never fires
        twin_m = MetricsLogger(validate=True, node="serve")
        twin = SLOEngine(
            [{"name": "shed-rate", "metric": "serving_requests_shed",
              "agg": "rate", "op": "<=", "threshold": 0.0,
              "window_s": 30.0}],
            snapshot_fn=twin_m.registry.snapshot, metrics=twin_m,
        )
        tb = Batcher(_SlowEngine(0.0), linger_s=0.0, metrics=twin_m,
                     max_queue=64)
        tb.start()
        try:
            for _ in range(5):
                tb.submit(np.ones((1, 10), np.float32)).result(timeout=30)
                twin.evaluate()
        finally:
            tb.stop()
        assert twin.ever_fired() == []
        assert twin_m.events("alert_pending") == []
