"""Crash-survival suite (tier-1): durable client sessions, idempotent
RPCs, the per-round recovery journal, server auto-recovery, the adaptive
liveness window, and the partition fault persona.

The `chaos` tests here run real gRPC federations in-process and kill the
server with `abort()` (the SIGKILL-equivalent: no stop broadcast, no
finalize) — the true process-level kills live in `tests/chaos/`
(slow-marked, run via `CHAOS=1 scripts/check.sh`).
"""

import os
import threading
import time

import grpc
import numpy as np
import pytest

from gfedntm_tpu.cli import build_parser
from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.federation import codec
from gfedntm_tpu.federation.client import Client, FederatedClientServicer
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.federation.registry import ACTIVE, ClientRecord, Federation
from gfedntm_tpu.federation.resilience import (
    FaultInjector,
    FaultSpec,
    InjectedRpcError,
    RetryPolicy,
)
from gfedntm_tpu.federation.server import FederatedServer, build_template_model
from gfedntm_tpu.train.checkpoint import (
    CheckpointIntegrityError,
    RoundJournal,
    atomic_write_bytes,
    atomic_write_json,
)
from gfedntm_tpu.utils.observability import MetricsLogger

MODEL_KWARGS = dict(
    n_components=3, hidden_sizes=(8,), batch_size=8, num_epochs=2, seed=0,
)


def _corpora(n_clients, docs, seed=0):
    rng = np.random.default_rng(seed)
    words = [f"tok{i:02d}" for i in range(45)]
    return [
        RawCorpus(documents=[
            " ".join(rng.choice(words, size=12)) for _ in range(docs)
        ])
        for _ in range(n_clients)
    ]


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---- atomic writes (satellite: kill mid-write can't truncate) ---------------

class TestAtomicWrites:
    def test_roundtrip_and_no_staging_residue(self, tmp_path):
        path = str(tmp_path / "state.json")
        atomic_write_json(path, {"round": 3})
        atomic_write_json(path, {"round": 4})
        import json

        assert json.load(open(path)) == {"round": 4}
        assert os.listdir(tmp_path) == ["state.json"]  # no .tmp leftovers

    def test_failed_replace_leaves_target_intact(self, tmp_path, monkeypatch):
        """A kill between the staging write and the rename (simulated by a
        failing os.replace) must leave the previous COMPLETE file — the
        truncated-JSON state PR 5's CheckpointIntegrityError detects can
        no longer be produced by the writer."""
        path = str(tmp_path / "meta.json")
        atomic_write_json(path, {"round": 1, "ok": True})

        def boom(src, dst):
            raise OSError("killed mid-rename")

        monkeypatch.setattr(os, "replace", boom)
        with pytest.raises(OSError):
            atomic_write_bytes(path, b'{"round": 2, "trunc')
        monkeypatch.undo()
        import json

        assert json.load(open(path)) == {"round": 1, "ok": True}
        assert os.listdir(tmp_path) == ["meta.json"]  # staging cleaned up

    def test_checkpoint_sidecar_partial_write_regression(self, tmp_path):
        """A sidecar produced by the atomic writer is all-or-nothing; a
        hand-truncated one (the pre-atomic failure mode) still surfaces
        loudly as CheckpointIntegrityError at load."""
        from gfedntm_tpu.train.checkpoint import FederationCheckpointer

        ckpt = FederationCheckpointer(str(tmp_path / "ck"))
        avg = {"w": np.ones((2, 2), np.float32)}
        ckpt.save_round(2, avg, [{"client_id": 1}], vocab=["a"])
        assert ckpt.load_meta()["round"] == 2
        with open(ckpt.meta_path, "w") as fh:
            fh.write('{"round": 2, "average_')  # simulated partial write
        with pytest.raises(CheckpointIntegrityError):
            ckpt.load_meta()


# ---- round journal ----------------------------------------------------------

class TestRoundJournal:
    AVG = {"p/beta": np.arange(6, dtype=np.float32).reshape(2, 3)}

    def test_record_load_roundtrip_with_aggregator_state(self, tmp_path):
        j = RoundJournal(str(tmp_path))
        assert j.load() is None
        j.record(
            5, self.AVG, [{"client_id": 1, "session_token": "tok"}],
            vocab=["a", "b"], extra={"family": "avitm", "aggregator": "x"},
            aggregator_state={"m": np.full(3, 2.0)},
        )
        state = j.load()
        assert state["round"] == 5 and state["family"] == "avitm"
        np.testing.assert_array_equal(state["average"]["p/beta"], self.AVG["p/beta"])
        np.testing.assert_array_equal(state["aggregator_state"]["m"], np.full(3, 2.0))
        assert state["membership"][0]["session_token"] == "tok"

    def test_corrupt_meta_is_loud(self, tmp_path):
        j = RoundJournal(str(tmp_path))
        j.record(1, self.AVG, [])
        with open(j.meta_path, "w") as fh:
            fh.write('{"round": 1, "aver')
        with pytest.raises(CheckpointIntegrityError):
            j.load()

    def test_halves_disagreeing_detected(self, tmp_path):
        """A kill between the npz and JSON writes leaves the meta one
        round behind the state file — detected, never mispaired."""
        j = RoundJournal(str(tmp_path))
        j.record(3, self.AVG, [])
        atomic_write_json(
            j.meta_path,
            {"round": 2, "average_keys": sorted(self.AVG), "membership": []},
        )
        with pytest.raises(CheckpointIntegrityError):
            j.load()

    def test_missing_state_file_is_loud(self, tmp_path):
        j = RoundJournal(str(tmp_path))
        j.record(1, self.AVG, [])
        os.unlink(j.state_path)
        with pytest.raises(CheckpointIntegrityError):
            j.load()

    def test_finished_marker_suppresses_load(self, tmp_path):
        j = RoundJournal(str(tmp_path))
        j.record(7, self.AVG, [])
        j.mark_finished()
        assert j.load() is None
        assert j.load_meta()["finished"] is True


# ---- session registry -------------------------------------------------------

class TestSessionRegistry:
    def test_join_classification_lifecycle(self):
        fed = Federation(min_clients=1)
        assert fed.classify_join(1, "") == "new"
        assert fed.classify_join(1, "tok") == "new"  # unknown client
        fed.set_session_token(1, "tok")
        assert fed.classify_join(1, "other") == "new"  # mismatch
        assert fed.classify_join(1, "tok") == "first"  # initial ready
        assert fed.classify_join(1, "tok") == "restore"  # reconnect
        assert fed.classify_join(1, "tok") == "restore"
        # re-mint (fresh process through GetGlobalSetup) resets the cycle
        fed.set_session_token(1, "tok2")
        assert fed.classify_join(1, "tok") == "new"
        assert fed.classify_join(1, "tok2") == "first"

    def test_codec_reset_is_consumed_once(self):
        fed = Federation(min_clients=1)
        fed.restore_member(1, session_token="t", needs_codec_reset=True)
        assert fed.consume_codec_reset(1) is True
        assert fed.consume_codec_reset(1) is False
        # minting clears any pending reset: a fresh process has no
        # session state to reset
        fed.restore_member(2, session_token="u", needs_codec_reset=True)
        fed.set_session_token(2, "u2")
        assert fed.consume_codec_reset(2) is False

    def test_restore_member_not_ready_until_reconnect(self):
        fed = Federation(min_clients=2)
        rec = fed.restore_member(
            1, nr_samples=40.0, session_token="t", current_mb=9,
            current_epoch=1,
        )
        assert not rec.ready_for_training and rec.status == ACTIVE
        assert fed.active_clients() == []
        fed.connect_ready(1, "localhost:1234")
        assert [c.client_id for c in fed.active_clients()] == [1]
        assert fed.get_clients()[0].nr_samples == 40.0

    def test_restored_finisher_stays_finished(self):
        fed = Federation(min_clients=1)
        rec = fed.restore_member(3, finished=True, session_token="t")
        assert rec.finished and fed.active_clients() == []


# ---- server session handling (no network) -----------------------------------

def _server(**kw):
    base = dict(min_clients=1, family="avitm", model_kwargs=MODEL_KWARGS)
    base.update(kw)
    return FederatedServer(**base)


class TestServerSessions:
    def test_mint_discards_old_process_state(self):
        server = _server()
        server.federation.connect_vocab(1, ("a",), 10.0)
        server._push_acked[1] = 4
        server._reply_seen[1] = 99
        server._poll_warmed.add(1)
        reply = server._mint_session(1, pb.GlobalSetup(codec_id="none"))
        assert reply.session_token
        assert 1 not in server._push_acked
        assert 1 not in server._reply_seen
        assert 1 not in server._poll_warmed
        # distinct tokens per mint, registry holds the latest
        again = server._mint_session(1, pb.GlobalSetup())
        assert again.session_token != reply.session_token
        assert server.federation.get_clients()[0].session_token == (
            again.session_token
        )

    def test_ready_with_token_restores_posture(self):
        m = MetricsLogger(validate=True)
        server = _server(min_clients=2, metrics=m)
        setup = server._mint_session(1, pb.GlobalSetup())
        token = setup.session_token
        # first ready of the fresh session: no restore accounting
        server.ReadyForTraining(
            pb.JoinRequest(client_id=1, address="localhost:1",
                           session_token=token), None,
        )
        assert m.registry.counter("session_restores").value == 0
        # a poll delivered a push meanwhile; then the connection dies and
        # the same live process reconnects: the ack survives
        server._push_acked[1] = 7
        server._poll_warmed.add(1)
        ack = server.ReadyForTraining(
            pb.JoinRequest(client_id=1, address="localhost:1",
                           session_token=token), None,
        )
        assert ack.code == 0
        assert server._push_acked.get(1) == 7
        assert 1 in server._poll_warmed
        assert m.registry.counter("session_restores").value == 1
        assert m.events("session_restored")[0]["client"] == 1

    def test_ready_without_token_clears_posture(self):
        server = _server(min_clients=2)
        server._mint_session(1, pb.GlobalSetup())
        server._push_acked[1] = 7
        server._poll_warmed.add(1)
        server._reply_seen[1] = 12
        server.ReadyForTraining(
            pb.JoinRequest(client_id=1, address="localhost:2"), None,
        )
        assert 1 not in server._push_acked
        assert 1 not in server._poll_warmed
        assert 1 not in server._reply_seen

    def test_recovered_server_orders_codec_reset_once(self):
        server = _server(min_clients=2, wire_codec="delta")
        server.federation.restore_member(
            1, session_token="tok", needs_codec_reset=True,
        )
        ack = server.ReadyForTraining(
            pb.JoinRequest(client_id=1, address="localhost:1",
                           codec_id="delta", session_token="tok"), None,
        )
        assert ack.code == 3  # reset your codec sessions
        ack2 = server.ReadyForTraining(
            pb.JoinRequest(client_id=1, address="localhost:1",
                           codec_id="delta", session_token="tok"), None,
        )
        assert ack2.code == 0  # consumed: ordered exactly once

    def test_step_seqs_are_monotonic(self):
        server = _server()
        seqs = [server._next_step_seq() for _ in range(100)]
        assert seqs == sorted(seqs) and len(set(seqs)) == 100

    def test_journal_every_zero_disables_autorecovery(self, tmp_path):
        """--journal_every 0 disables the journal AND auto-recovery (the
        documented contract): without the journal's finished stamp, a
        cleanly-completed run's checkpoints would otherwise be
        resurrected on every restart. Explicit --resume still restores
        them."""
        from gfedntm_tpu.train.checkpoint import FederationCheckpointer

        ckpt_dir = str(tmp_path / "checkpoints")
        template = build_template_model("avitm", 30, MODEL_KWARGS)
        server0 = _server(save_dir=str(tmp_path))
        server0.template = template
        avg = {k: np.asarray(v)
               for k, v in server0._shared_template().items()}
        FederationCheckpointer(ckpt_dir).save_round(
            4, avg, [{"client_id": 1, "nr_samples": 8.0}],
            vocab=[f"t{i}" for i in range(30)],
            extra={"family": "avitm", "aggregator": "fedavg",
                   "wire_codec": "none"},
        )
        server = _server(save_dir=str(tmp_path), journal_every=0)
        assert server.maybe_autorecover() is None
        resumed = _server(save_dir=str(tmp_path), journal_every=0)
        assert resumed.restore_from_checkpoint() == 4  # --resume still works


# ---- idempotent RPCs: client servicer ---------------------------------------

class _CountingStepper:
    """Minimal FederatedStepper stand-in counting mutations."""

    def __init__(self):
        self.steps = 0
        self.applies = 0
        self.loss = 1.0
        self._last_batch_size = 8.0
        self.current_mb = 0
        self.current_epoch = 0
        self.finished = False
        self.steps_remaining = 1000

    def train_mb_delta(self, snapshot=True):
        self.steps += 1
        self.current_mb += 1
        return {"w": np.full((2,), float(self.steps), np.float32)}

    def advance_local(self):
        pass

    def delta_update_fit(self, average):
        self.applies += 1

        class _S:
            epoch_ended = False
            finished = False
            current_epoch = 0

        return _S()


def _servicer(metrics=None):
    import logging

    stepper = _CountingStepper()
    return stepper, FederatedClientServicer(
        client_id=1, stepper=stepper, on_stop=lambda: None,
        logger=logging.getLogger("test"), metrics=metrics,
    )


class TestIdempotentServicer:
    def test_replayed_trainstep_answered_from_cache(self):
        m = MetricsLogger(validate=True)
        stepper, servicer = _servicer(metrics=m)
        first = servicer.TrainStep(
            pb.StepRequest(global_iter=0, local_steps=1, seq=101), None,
        )
        assert stepper.steps == 1 and first.seq == 101
        replay = servicer.TrainStep(
            pb.StepRequest(global_iter=0, local_steps=1, seq=101), None,
        )
        assert stepper.steps == 1  # did NOT run more local steps
        assert replay.SerializeToString() == first.SerializeToString()
        assert m.registry.counter("rpcs_deduplicated").value == 1
        assert m.events("rpc_deduplicated")[0]["method"] == "TrainStep"
        # a FRESH seq advances training again
        nxt = servicer.TrainStep(
            pb.StepRequest(global_iter=1, local_steps=1, seq=102), None,
        )
        assert stepper.steps == 2 and nxt.seq == 102

    def test_seqless_requests_never_cached(self):
        stepper, servicer = _servicer()
        servicer.TrainStep(pb.StepRequest(global_iter=0, local_steps=1), None)
        servicer.TrainStep(pb.StepRequest(global_iter=0, local_steps=1), None)
        assert stepper.steps == 2  # legacy servers keep legacy semantics

    def test_replayed_push_ignored_reset_exempt(self):
        m = MetricsLogger(validate=True)
        stepper, servicer = _servicer(metrics=m)
        bundle = codec.flatdict_to_bundle({"w": np.zeros(2, np.float32)})
        servicer.ApplyAggregate(pb.Aggregate(shared=bundle, round=0), None)
        assert stepper.applies == 1
        # replay of round 0: ignored
        servicer.ApplyAggregate(pb.Aggregate(shared=bundle, round=0), None)
        assert stepper.applies == 1
        assert m.registry.counter("rpcs_deduplicated").value == 1
        # next round applies; then a reset_session replay of the SAME
        # round applies too (rollback/recovery re-broadcasts re-deliver)
        servicer.ApplyAggregate(pb.Aggregate(shared=bundle, round=1), None)
        assert stepper.applies == 2
        servicer.ApplyAggregate(
            pb.Aggregate(shared=bundle, round=1, reset_session=True), None,
        )
        assert stepper.applies == 3


# ---- idempotent RPCs: server-side reply dedup -------------------------------

class TestServerReplyDedup:
    def test_duplicate_step_reply_dropped_from_average(self):
        m = MetricsLogger(validate=True)
        server = _server(metrics=m, sanitize=False)
        server.global_vocab = None
        server.template = build_template_model("avitm", 30, MODEL_KWARGS)
        snap = {
            k: np.asarray(v)
            for k, v in server._shared_template().items()
        }
        rec = ClientRecord(client_id=1, nr_samples=10.0)
        reply = pb.StepReply(
            client_id=1, shared=codec.flatdict_to_bundle(snap),
            loss=1.0, nr_samples=8.0, seq=500,
        )
        out = server._collect_snapshots([(rec, reply), (rec, reply)], 0)
        assert len(out) == 1  # one step, one vote
        assert m.registry.counter("rpcs_deduplicated").value == 1
        # the SAME seq later (e.g. a ghost retry) is still deduplicated
        out2 = server._collect_snapshots([(rec, reply)], 1)
        assert len(out2) == 0
        # a fresh seq is admitted again
        fresh = pb.StepReply(
            client_id=1, shared=codec.flatdict_to_bundle(snap),
            loss=1.0, nr_samples=8.0, seq=501,
        )
        assert len(server._collect_snapshots([(rec, fresh)], 2)) == 1


# ---- idempotent retry policy ------------------------------------------------

class TestIdempotentRetry:
    def test_deadline_retry_requires_idempotent_mode(self):
        exc = InjectedRpcError(grpc.StatusCode.DEADLINE_EXCEEDED, "slow")
        assert not RetryPolicy().retryable(exc)
        assert RetryPolicy(idempotent=True).retryable(exc)
        # non-gRPC permanents stay permanent either way
        assert not RetryPolicy(idempotent=True).retryable(ValueError("x"))

    def test_deadline_exceeded_retried_when_idempotent(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 2:
                raise InjectedRpcError(
                    grpc.StatusCode.DEADLINE_EXCEEDED, "slow"
                )
            return "ok"

        p = RetryPolicy(max_attempts=3, idempotent=True, seed=0,
                        sleep=lambda _s: None)
        assert p.call(flaky) == "ok" and calls["n"] == 2

    def test_server_client_stubs_get_idempotent_twin(self):
        base = RetryPolicy(max_attempts=5, seed=3)
        server = _server(retry_policy=base)
        assert server.retry_policy.idempotent is False
        assert server.client_retry_policy.idempotent is True
        assert server.client_retry_policy.max_attempts == 5


# ---- partition fault persona ------------------------------------------------

class TestPartitionFault:
    def test_blackholes_peer_for_window_then_heals(self):
        m = MetricsLogger(validate=True)
        inj = FaultInjector(seed=0, metrics=m)
        inj.script("*", kind="partition", delay_s=0.15, peer="client2")
        for _ in range(3):  # every call in the window fails, any method
            with pytest.raises(InjectedRpcError):
                inj.before_call("svc", "TrainStep", peer="client2")
        with pytest.raises(InjectedRpcError):
            inj.before_call("svc", "ApplyAggregate", peer="client2")
        inj.before_call("svc", "TrainStep", peer="client1")  # unaffected
        time.sleep(0.2)
        inj.before_call("svc", "TrainStep", peer="client2")  # healed
        assert m.events("partition_injected")[0]["peer"] == "client2"
        assert m.registry.counter("partitions_injected").value == 1
        assert all(k == "partition" for _m, _p, k in inj.fired)

    def test_partition_needs_positive_window(self):
        with pytest.raises(ValueError):
            FaultSpec(method="*", kind="partition")


# ---- adaptive liveness window -----------------------------------------------

class TestAdaptiveWatchdog:
    def _client(self, **kw):
        base = dict(
            client_id=1, corpus=RawCorpus(documents=["a b"]),
            server_address="localhost:1", liveness_timeout=300.0,
        )
        base.update(kw)
        return Client(**base)

    def test_cold_start_uses_fixed_formula(self):
        c = self._client()
        assert c._watchdog_window() == 300.0
        c._note_local_steps(150)  # 120+2E deadline scale
        assert c._watchdog_window() == pytest.approx(300.0 * 3.5)

    def test_observed_cadence_shrinks_window_when_reconnect_cheap(self):
        c = self._client(reconnect_window=120.0)
        c.session_token = "tok"
        for _ in range(5):  # ~0.1 s inter-poll gaps
            c._last_activity = time.monotonic() - 0.1
            c._rpc_begin()
            c._rpc_end()
        w = c._watchdog_window()
        assert 5.0 <= w <= 11.0  # margin + headroom x ewma, floored
        assert w < 300.0  # dead server detected in seconds, not minutes

    def test_slow_server_only_widens_destructive_window(self):
        """The premature-finalize fix: a server legitimately pacing
        slower than the configured window must not read as dead when
        firing means self-finalize (no reconnect available)."""
        c = self._client(liveness_timeout=30.0, reconnect_window=0.0)
        c._last_activity = time.monotonic() - 60.0
        c._rpc_begin()  # one observed 60 s gap
        c._rpc_end()
        assert c._watchdog_window() > 300.0  # widened well past fixed 30
        # with reconnect available the window is capped at the
        # operator's own bound instead
        c2 = self._client(liveness_timeout=30.0, reconnect_window=120.0)
        c2.session_token = "tok"
        c2._last_activity = time.monotonic() - 60.0
        c2._rpc_begin()
        c2._rpc_end()
        assert c2._watchdog_window() == pytest.approx(30.0)

    def test_finished_client_never_reconnects(self):
        """An early finisher waiting for the fleet-wide stop broadcast
        sees the server go legitimately quiet (finished members are not
        polled): probing ReadyForTraining then would re-enroll it as
        unfinished server-side and flap it through pointless extra polls
        — reconnect is off, and the window reverts to the conservative
        widen-only branch."""
        c = self._client(liveness_timeout=30.0, reconnect_window=120.0)
        c.session_token = "tok"

        class _DoneStepper:
            finished = True

        c.stepper = _DoneStepper()
        assert not c._reconnect_available()
        c._last_activity = time.monotonic() - 60.0
        c._rpc_begin()
        c._rpc_end()
        assert c._watchdog_window() > 300.0  # widen-only, not capped at 30
        c.stepper.finished = False
        assert c._reconnect_available()


# ---- CLI flags --------------------------------------------------------------

def test_parser_survival_flags():
    p = build_parser()
    args = p.parse_args([])
    assert args.reconnect_window == 180.0
    assert args.journal_every == 1
    assert args.no_autorecover is False
    assert args.chaos is None
    args = p.parse_args(
        ["--reconnect_window", "0", "--journal_every", "5",
         "--no_autorecover", "--chaos", "[]"]
    )
    assert args.reconnect_window == 0.0 and args.journal_every == 5
    assert args.no_autorecover and args.chaos == "[]"


# ---- chaos e2e: in-process kills over real gRPC -----------------------------

def _run_clients(clients):
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    return threads


def _await_round(server, round_idx, timeout=240.0):
    deadline = time.time() + timeout
    while time.time() < deadline and server.global_iterations < round_idx:
        time.sleep(0.05)
    assert server.global_iterations >= round_idx, (
        f"never reached round {round_idx}"
    )


def _abort_and_join(server):
    """In-process SIGKILL stand-in: abort, then wait for the abandoned
    training thread to actually exit — a REAL kill takes the thread with
    the process, but in-process it would otherwise race the replacement
    server's recovery reads with its final journal write."""
    server.abort()
    t = server._train_thread
    if t is not None:
        t.join(timeout=120.0)
        assert not t.is_alive(), "aborted training thread never exited"


@pytest.mark.chaos
def test_server_kill_autorecovery_with_session_reconnect(tmp_path):
    """The tentpole flow end to end (in-process): a hard-killed server is
    replaced by a fresh process with ZERO operator flags — it auto-resumes
    from the round journal, re-admits both clients via their session
    tokens (codec reset ordered per member, delta codec stays
    consistent: codec_ref_miss == 0), and the federation trains to finite
    betas."""
    port = _free_port()
    srv_dir = str(tmp_path / "server")
    kwargs = dict(MODEL_KWARGS, num_epochs=3)
    m1 = MetricsLogger(str(tmp_path / "run1.jsonl"), validate=True)
    server1 = FederatedServer(
        min_clients=2, family="avitm", model_kwargs=kwargs, max_iters=80,
        save_dir=srv_dir, metrics=m1, checkpoint_every=0,
        wire_codec="delta",
    )
    server1.start(f"[::]:{port}")
    mc = MetricsLogger(validate=True)
    clients = [
        Client(client_id=c + 1, corpus=corpus,
               server_address=f"localhost:{port}", max_features=45,
               save_dir=str(tmp_path / f"c{c + 1}"), metrics=mc,
               liveness_timeout=60.0, watchdog_poll_s=0.1,
               reconnect_window=120.0, wire_codec="delta")
        for c, corpus in enumerate(_corpora(2, docs=40, seed=3))
    ]
    threads = _run_clients(clients)
    _await_round(server1, 4)
    _abort_and_join(server1)  # SIGKILL-equivalent: no broadcast/finalize
    killed_at = server1.global_iterations
    m1.close()

    # a replacement process: same construction, NO resume flag
    m2 = MetricsLogger(str(tmp_path / "run2.jsonl"), validate=True)
    server2 = FederatedServer(
        min_clients=2, family="avitm", model_kwargs=kwargs, max_iters=80,
        save_dir=srv_dir, metrics=m2, checkpoint_every=0,
        wire_codec="delta",
    )
    resumed = server2.maybe_autorecover()
    assert resumed is not None and resumed >= killed_at - 1
    assert server2._recovered_source == "journal"
    server2.start(f"[::]:{port}")
    try:
        assert server2.wait_done(timeout=600), "recovered run did not finish"
        for t in threads:
            t.join(timeout=60)
    finally:
        server2.stop()
        for c in clients:
            c.shutdown()
        m2.close()
        mc.close()

    for c in clients:
        assert c.stopped.is_set() and c.stepper.finished
    assert np.isfinite(server2.global_betas).all()
    assert server2.global_iterations > resumed
    # both clients came back as the SAME live processes
    assert m2.registry.counter("session_restores").value == 2
    assert mc.registry.counter("client_reconnections").value == 2
    for ev in mc.events("client_reconnected"):
        assert ev["attempts"] >= 1
    # delta-codec posture healed by the per-member reset order: no
    # undecodable uplinks anywhere in the recovered run
    assert m2.registry.counter("codec_ref_miss").value == 0
    assert mc.registry.counter("codec_ref_miss").value == 0
    # no double-counted replies either side of the kill
    assert m2.registry.counter("rpcs_deduplicated").value == 0
    # and the finished run does not resurrect
    server3 = FederatedServer(
        min_clients=2, family="avitm", model_kwargs=kwargs, max_iters=80,
        save_dir=srv_dir, checkpoint_every=0, wire_codec="delta",
    )
    assert server3.maybe_autorecover() is None


@pytest.mark.chaos
def test_autorecovery_composes_with_cohort_pacing(tmp_path):
    """Satellite: --resume/auto-recovery x cohort pacing. The restored
    `_push_acked` round tags start empty, the rotating cohort gets
    self-contained pushes, and the delta codec stays consistent
    (codec_ref_miss == 0) through the restart."""
    port = _free_port()
    srv_dir = str(tmp_path / "server")
    kwargs = dict(MODEL_KWARGS, num_epochs=3)
    m1 = MetricsLogger(validate=True)
    mk = dict(
        min_clients=3, family="avitm", model_kwargs=kwargs, max_iters=80,
        save_dir=srv_dir, checkpoint_every=0, wire_codec="delta",
        pacing_policy="cohort", cohort_size=2, pacing_seed=5,
    )
    server1 = FederatedServer(metrics=m1, **mk)
    server1.start(f"[::]:{port}")
    clients = [
        Client(client_id=c + 1, corpus=corpus,
               server_address=f"localhost:{port}", max_features=45,
               save_dir=str(tmp_path / f"c{c + 1}"),
               liveness_timeout=60.0, watchdog_poll_s=0.1,
               reconnect_window=120.0, wire_codec="delta")
        for c, corpus in enumerate(_corpora(3, docs=40, seed=4))
    ]
    threads = _run_clients(clients)
    _await_round(server1, 4)
    _abort_and_join(server1)

    m2 = MetricsLogger(validate=True)
    server2 = FederatedServer(metrics=m2, **mk)
    resumed = server2.maybe_autorecover()
    assert resumed is not None and resumed >= 3
    server2.start(f"[::]:{port}")
    try:
        assert server2.wait_done(timeout=600), "cohort recovery stalled"
        for t in threads:
            t.join(timeout=60)
    finally:
        server2.stop()
        for c in clients:
            c.shutdown()

    for c in clients:
        assert c.stopped.is_set() and c.stepper.finished
    assert np.isfinite(server2.global_betas).all()
    assert m2.registry.counter("codec_ref_miss").value == 0
    assert m2.registry.counter("session_restores").value >= 2
    # cohort sampling actually ran after the restart
    assert m2.events("cohort_sampled")


@pytest.mark.chaos
def test_autorecovery_composes_with_async_pacing(tmp_path):
    """Satellite: auto-recovery x buffered-async pacing. Buffered
    `base_round` tags older than the restart are reconciled — the clamped
    staleness never goes negative or explodes — and the recovered run
    drains to finite betas."""
    port = _free_port()
    srv_dir = str(tmp_path / "server")
    kwargs = dict(MODEL_KWARGS, num_epochs=3)
    mk = dict(
        min_clients=3, family="avitm", model_kwargs=kwargs, max_iters=120,
        save_dir=srv_dir, checkpoint_every=0,
        pacing_policy="async", async_buffer=2, staleness_alpha=0.5,
    )
    m1 = MetricsLogger(validate=True)
    server1 = FederatedServer(metrics=m1, **mk)
    server1.start(f"[::]:{port}")
    clients = [
        Client(client_id=c + 1, corpus=corpus,
               server_address=f"localhost:{port}", max_features=45,
               save_dir=str(tmp_path / f"c{c + 1}"),
               liveness_timeout=60.0, watchdog_poll_s=0.1,
               reconnect_window=120.0)
        for c, corpus in enumerate(_corpora(3, docs=40, seed=6))
    ]
    threads = _run_clients(clients)
    _await_round(server1, 4)
    _abort_and_join(server1)

    m2 = MetricsLogger(validate=True)
    server2 = FederatedServer(metrics=m2, **mk)
    resumed = server2.maybe_autorecover()
    assert resumed is not None and resumed >= 3
    server2.start(f"[::]:{port}")
    try:
        assert server2.wait_done(timeout=600), "async recovery stalled"
        for t in threads:
            t.join(timeout=60)
    finally:
        server2.stop()
        for c in clients:
            c.shutdown()

    for c in clients:
        assert c.stopped.is_set() and c.stepper.finished
    assert np.isfinite(server2.global_betas).all()
    assert server2.global_iterations > resumed
    # stale buffered updates spanning the restart were discounted, not
    # rejected: every surviving client kept contributing
    for ev in m2.events("update_stale_discounted"):
        assert ev["staleness"] >= 0 and 0 < ev["factor"] <= 1.0


@pytest.mark.chaos
def test_partition_persona_survivors_converge(tmp_path):
    """A partitioned client (every RPC to it blackholed for a window)
    rides probation through the outage, recovers when the window lifts,
    and the federation converges with ALL clients contributing finite
    state — the process-level partition story, in-process."""
    m = MetricsLogger(validate=True)
    inj = FaultInjector(seed=0, metrics=m)
    inj.script("*", kind="partition", peer="client2", delay_s=2.0)
    server = FederatedServer(
        min_clients=3, family="avitm", model_kwargs=MODEL_KWARGS,
        max_iters=80, save_dir=str(tmp_path / "server"), metrics=m,
        checkpoint_every=0, fault_injector=inj,
        retry_policy=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                                 max_delay_s=0.05, seed=1),
        probation_rounds=10, round_backoff_s=0.1,
    )
    addr = server.start("[::]:0")
    clients = [
        Client(client_id=c + 1, corpus=corpus, server_address=addr,
               max_features=45, save_dir=str(tmp_path / f"c{c + 1}"))
        for c, corpus in enumerate(_corpora(3, docs=40, seed=8))
    ]
    threads = _run_clients(clients)
    try:
        assert server.wait_done(timeout=600), "partition run stalled"
        for t in threads:
            t.join(timeout=60)
    finally:
        server.stop()
        for c in clients:
            c.shutdown()

    for c in clients:
        assert c.stopped.is_set() and c.stepper.finished
        assert np.isfinite(c.results["betas"]).all()
    assert np.isfinite(server.global_betas).all()
    ev = m.events("partition_injected")
    assert ev and ev[0]["peer"] == "client2"
    # the partitioned client went suspect during the window and was
    # polled back in afterwards — it trained to completion like its peers
    recs = {r.client_id: r for r in server.federation.get_clients()}
    assert recs[2].finished
    assert clients[1].stepper.current_epoch == MODEL_KWARGS["num_epochs"]
    assert m.registry.counter("client_drops").value == 0
