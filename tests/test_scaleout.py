"""Wire-efficient scale-out tests (ISSUE 11): per-recipient delta
encoding over the canonical view chain, bounded/instrumented reference
caches, client-initiated push pacing, the hierarchical relay tier, the
O(N)-safe ``/status`` summary, and the per-tier wire accounting surfaced
by ``summarize``.

The acceptance scenarios — rotating-cohort delta compression > 2x vs the
PR 10 fleet-consensus (self-contained) behaviour, ReferenceMismatch
healing under a deliberately undersized cache, 2-relay/flat beta parity,
a poisoner contained behind a relay, and the 1k-simulated-client
loopback smoke — are all here (the two multi-federation relay runs and
the 1k smoke are ``slow``-marked).
"""

import json
import math
import threading
import time
import urllib.request

import numpy as np
import pytest

from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.federation import codec
from gfedntm_tpu.federation.client import Client
from gfedntm_tpu.federation.compression import (
    DownlinkDecoder,
    DownlinkEncoder,
    ReferenceMismatch,
    UplinkDecoder,
    UplinkEncoder,
    WireCodec,
)
from gfedntm_tpu.federation.pacing import PushEngine, parse_pacing
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.federation.registry import Federation
from gfedntm_tpu.federation.relay import RelayNode
from gfedntm_tpu.federation.resilience import FaultInjector
from gfedntm_tpu.federation.server import FederatedServer
from gfedntm_tpu.federation.simfleet import make_sim_fleet
from gfedntm_tpu.utils.observability import (
    MetricsLogger,
    collect_wire_tiers,
    format_wire_tiers,
)

MODEL_KWARGS = dict(
    n_components=3, hidden_sizes=(8,), batch_size=8, num_epochs=2, seed=0,
)


def _state(d=512, seed=0):
    rng = np.random.default_rng(seed)
    return {"plane": rng.standard_normal(d).astype(np.float32)}


def _walk(state, scale=1e-3, seed=1):
    rng = np.random.default_rng(seed)
    return {
        k: v + scale * rng.standard_normal(v.shape).astype(v.dtype)
        for k, v in state.items()
    }


# ---- per-recipient downlink encoding (the tentpole's codec layer) -----------

class TestPerRecipientEncoding:
    def test_chain_catchup_and_selfcontained_variants(self):
        enc = DownlinkEncoder(WireCodec("delta+topk:0.25"), max_views=8)
        s0 = _state(seed=0)
        enc.advance(s0, 0)
        s1 = _walk(s0, seed=1)
        chain1, view1 = enc.advance(s1, 1)
        assert chain1.ref_round == 1  # delta vs round 0
        # up to date -> the shared chain bundle object itself
        assert enc.bundle_for(0) is chain1
        s2 = _walk(s1, seed=2)
        chain2, view2 = enc.advance(s2, 2)
        # behind but cached -> catch-up tagged against the OLD round
        catchup = enc.bundle_for(0)
        assert catchup.ref_round == 1
        assert {r.codec for r in catchup.tensors} <= {"sparse_set", "raw", ""}
        # no reference at all -> self-contained view bundle
        fresh = enc.bundle_for(None)
        assert fresh.ref_round == 0

    def test_catchup_reconstructs_canonical_view_bit_exactly(self):
        """The exactness invariant that makes per-recipient encoding
        safe: EVERY recipient of round r — chain, catch-up, or
        self-contained — must hold the identical canonical view, or the
        uplink reference chain silently corrupts. Assignment records
        (sparse_set) are what guarantees it: an additive float delta
        would drift by an ulp."""
        wc = WireCodec("delta+topk:0.2+fp16")
        enc = DownlinkEncoder(wc, max_views=8)
        behind = DownlinkDecoder(wc)
        fresh = DownlinkDecoder(wc)
        current = DownlinkDecoder(wc)
        state = _state(seed=3)
        b0, _ = enc.advance(state, 0)
        for dec in (behind, fresh, current):
            dec.decode(b0, round_idx=0)
        views = {}
        for r in range(1, 5):
            state = _walk(state, seed=10 + r)
            chain, view = enc.advance(state, r)
            views[r] = view
            current.decode(chain, round_idx=r)
        # `behind` stayed on round 0 -> catch-up onto round 4's view
        got_behind = behind.decode(enc.bundle_for(0), round_idx=4)
        # `fresh` lost its state entirely -> self-contained view bundle
        fresh.reset()
        got_fresh = fresh.decode(enc.bundle_for(None), round_idx=4)
        got_chain = current._ref
        for name, want in views[4].items():
            np.testing.assert_array_equal(got_behind[name], want)
            np.testing.assert_array_equal(got_fresh[name], want)
            np.testing.assert_array_equal(got_chain[name], want)

    def test_catchup_mismatched_reference_fails_loudly(self):
        wc = WireCodec("delta")
        enc = DownlinkEncoder(wc, max_views=8)
        dec = DownlinkDecoder(wc)
        s = _state(seed=4)
        enc.advance(s, 0)
        dec.decode(enc.bundle_for(None), round_idx=0)
        s = _walk(s)
        enc.advance(s, 1)
        s = _walk(s, seed=9)
        enc.advance(s, 2)
        # decoder holds round 0; a chain bundle for round-1 holders must
        # NOT decode against it
        with pytest.raises(ReferenceMismatch):
            dec.decode(enc.bundle_for(1), round_idx=2)

    def test_server_encodes_per_recipient_groups(self, tmp_path):
        from gfedntm_tpu.federation.server import build_template_model

        server = FederatedServer(
            min_clients=2, family="avitm", model_kwargs=MODEL_KWARGS,
            wire_codec="delta", save_dir=str(tmp_path),
        )
        server.template = build_template_model("avitm", 30, MODEL_KWARGS)
        tmpl = server._shared_template()
        from gfedntm_tpu.federation.registry import ClientRecord

        recs = [ClientRecord(i) for i in (1, 2, 3)]
        reply = pb.StepReply(client_id=1)
        replies = [(r, reply) for r in recs]
        aggs0 = server._encode_push(tmpl, 0, replies)
        assert {a.shared.ref_round for a in aggs0.values()} == {0}
        with server._push_lock:
            server._push_acked.update({1: 0, 2: 0})
        aggs1 = server._encode_push(tmpl, 1, replies)
        # 1 and 2 share the chain delta; 3 gets its own self-contained
        assert aggs1[1] is aggs1[2]
        assert aggs1[1].shared.ref_round == 1
        assert aggs1[3].shared.ref_round == 0


# ---- bounded + instrumented reference caches (satellite) --------------------

class TestBoundedReferenceCaches:
    def test_uplink_eviction_counter_age_gauge_and_event(self):
        m = MetricsLogger(validate=True)
        dec = UplinkDecoder(WireCodec("delta"), metrics=m, max_refs=2)
        view = _state(seed=5)
        for r in range(4):
            dec.note_push(r, view)
        assert m.registry.counter("codec_refs_evicted").value == 2
        events = m.events("codec_ref_evicted")
        assert [e["round"] for e in events] == [0, 1]
        assert all(e["direction"] == "uplink" for e in events)
        # age of the last eviction: round 1 evicted while noting round 3
        gauge = m.registry.gauge("codec_ref_evicted_age_rounds/uplink")
        assert gauge.value == 2

    def test_uplink_eviction_is_loud_reference_miss_not_misdecode(self):
        wc = WireCodec("delta")
        m = MetricsLogger(validate=True)
        dec = UplinkDecoder(wc, metrics=m, max_refs=1)
        enc = UplinkEncoder(wc)
        v0, v1 = _state(seed=6), _state(seed=7)
        dec.note_push(0, v0)
        dec.note_push(1, v1)  # evicts round 0
        enc.note_aggregate(v0, 0)
        bundle = enc.encode(_walk(v0))
        with pytest.raises(ReferenceMismatch):
            dec.decode(bundle)

    def test_downlink_eviction_degrades_to_selfcontained_push(self):
        """Satellite acceptance: an evicted downlink reference costs the
        recipient a self-contained (still exact) push — never an
        error."""
        m = MetricsLogger(validate=True)
        wc = WireCodec("delta+topk:0.25")
        enc = DownlinkEncoder(wc, metrics=m, max_views=2)
        dec = DownlinkDecoder(wc)
        state = _state(seed=8)
        enc.advance(state, 0)
        dec.decode(enc.bundle_for(None), round_idx=0)
        views = {}
        for r in range(1, 5):  # max_views=2: round 0 evicted well before 4
            state = _walk(state, seed=20 + r)
            _, views[r] = enc.advance(state, r)
        assert any(
            e["direction"] == "downlink"
            for e in m.events("codec_ref_evicted")
        )
        bundle = enc.bundle_for(0)  # recipient still on evicted round 0
        assert bundle.ref_round == 0  # self-contained, not a catch-up
        got = dec.decode(bundle, round_idx=4)
        for name, want in views[4].items():
            np.testing.assert_array_equal(got[name], want)
        assert m.registry.counter("codec_selfcontained_pushes").value >= 1

    def test_server_caps_rotation_autosize(self, tmp_path):
        server = FederatedServer(
            min_clients=1, family="avitm", model_kwargs=MODEL_KWARGS,
            wire_codec="delta", pacing_policy="cohort:2",
            codec_ref_cache_max=16, save_dir=str(tmp_path),
        )
        for cid in range(1, 201):
            server.federation.connect_vocab(cid, (), 1.0)
        server._size_codec_caches()
        # uncapped would be 4 * ceil(200 / 2) = 400
        assert server._uplink_dec.max_refs == 16
        assert server._downlink_enc.max_views == 16


# ---- rotating-cohort compression ratio (satellite acceptance) ---------------

def _rotation_bytes(n, k, rounds, codec_spec, d=30_000, max_views=None):
    """Sent bytes under strict K-of-N rotation: per-recipient encoding
    vs the PR 10 rule (rotation => every push self-contained)."""
    rng = np.random.default_rng(0)
    state = {"plane": rng.standard_normal(d).astype(np.float32)}
    wc = WireCodec(codec_spec)
    enc_new = DownlinkEncoder(
        wc, max_views=max_views or 4 * math.ceil(n / k)
    )
    enc_old = DownlinkEncoder(WireCodec(codec_spec))
    acked = {}
    new_bytes = old_bytes = 0
    ref_misses = 0
    dec = {cid: DownlinkDecoder(wc) for cid in range(n)}
    for r in range(rounds):
        state = {
            "plane": state["plane"]
            + 1e-3 * rng.standard_normal(d).astype(np.float32)
        }
        enc_new.advance(state, r)
        cohort = [(r * k + j) % n for j in range(k)]
        for cid in cohort:
            bundle = enc_new.bundle_for(acked.get(cid))
            new_bytes += bundle.ByteSize()
            try:
                dec[cid].decode(bundle, round_idx=r)
            except ReferenceMismatch:
                ref_misses += 1
                dec[cid].reset()
                dec[cid].decode(enc_new.bundle_for(None), round_idx=r)
            acked[cid] = r
        old_bundle, _ = enc_old.encode(state, r, allow_delta=False)
        old_bytes += old_bundle.ByteSize() * k
    return new_bytes, old_bytes, ref_misses


def test_rotating_cohort_keeps_compression_over_2x():
    """ISSUE 11 acceptance: K-of-N rotation over enough rounds to cycle
    the (rightly-sized) cache keeps every recipient decodable with zero
    reference misses, at a measured > 2x sent-bytes reduction vs the
    PR 10 self-contained behaviour."""
    n, k = 24, 4  # rotation span 6; 24 rounds = 4 full cache cycles
    new_bytes, old_bytes, misses = _rotation_bytes(
        n, k, rounds=24, codec_spec="delta+topk:0.02"
    )
    assert misses == 0
    ratio = old_bytes / new_bytes
    assert ratio > 2.0, f"per-recipient ratio only {ratio:.2f}x"


def test_undersized_cache_heals_via_reference_mismatch():
    """The deliberately-undersized-cache shape: evicted references force
    self-contained re-syncs (loud, healed) — never a mis-decode, and the
    recipients keep converging onto the canonical view."""
    new_bytes, old_bytes, misses = _rotation_bytes(
        12, 2, rounds=18, codec_spec="delta+topk:0.1", max_views=1,
    )
    # max_views=1 keeps only the newest view: every behind recipient
    # falls back to a self-contained view bundle (ref misses impossible
    # on THIS path because bundle_for degrades before encoding a ref the
    # cache lost — the miss path needs the uplink direction, covered in
    # TestBoundedReferenceCaches).
    assert misses == 0
    assert new_bytes <= old_bytes * 1.05


# ---- push pacing ------------------------------------------------------------

class TestPushPacing:
    def test_parse_push_spec(self):
        spec = parse_pacing("push:4")
        assert (spec.policy, spec.buffer_size, spec.spec_id) == (
            "push", 4, "push:4",
        )
        with pytest.raises(ValueError):
            parse_pacing("push")

    def test_push_update_holds_before_training_starts(self, tmp_path):
        server = FederatedServer(
            min_clients=2, family="avitm", model_kwargs=MODEL_KWARGS,
            pacing_policy="push:2", save_dir=str(tmp_path),
        )
        server.federation.connect_vocab(1, (), 1.0)
        server.federation.set_session_token(1, "tok1")
        agg = server.PushUpdate(
            pb.StepReply(client_id=1, session_token="tok1"), None
        )
        assert agg.round == -1 and not agg.stop
        assert not len(agg.shared.tensors)

    def test_push_update_refuses_stale_token(self, tmp_path):
        m = MetricsLogger(validate=True)
        server = FederatedServer(
            min_clients=2, family="avitm", model_kwargs=MODEL_KWARGS,
            pacing_policy="push:2", metrics=m, save_dir=str(tmp_path),
        )
        server.federation.connect_vocab(1, (), 1.0)
        server.federation.set_session_token(1, "current")
        agg = server.PushUpdate(
            pb.StepReply(client_id=1, session_token="stale"), None
        )
        assert agg.stop
        assert m.registry.counter("push_updates_refused").value == 1

    def test_push_update_refused_under_poll_pacing(self, tmp_path):
        server = FederatedServer(
            min_clients=2, family="avitm", model_kwargs=MODEL_KWARGS,
            pacing_policy="sync", save_dir=str(tmp_path),
        )
        agg = server.PushUpdate(pb.StepReply(client_id=1), None)
        assert agg.stop

    def test_setup_advertises_pacing_and_local_steps(self, tmp_path):
        server = FederatedServer(
            min_clients=1, family="avitm", model_kwargs=MODEL_KWARGS,
            pacing_policy="push:3", local_steps=2, save_dir=str(tmp_path),
        )
        server.federation.connect_vocab(1, ("tok",), 4.0)
        reply = server.GetGlobalSetup(pb.JoinRequest(client_id=1), None)
        assert reply.pacing_id == "push:3"
        assert reply.local_steps == 2

    def test_push_update_duplicate_seq_not_double_buffered(self, tmp_path):
        """A stub-level retry of a delivered-but-reply-lost push must not
        buffer (and average) the update twice: client-minted push seqs
        dedup at the servicer, while the duplicate still receives the
        freshest broadcast."""
        m = MetricsLogger(validate=True)
        server, servicers, template = make_sim_fleet(
            2, steps=10, pacing_policy="push:8", max_iters=5,
            save_dir=str(tmp_path), checkpoint_every=0, journal_every=0,
            metrics=m,
        )
        try:
            update = servicers[1].build_update(template, seq=7)
            server.PushUpdate(update, None)
            server.PushUpdate(update, None)  # the retry
            engine = server._engine
            assert engine.status()["buffer_depth"] == 1
            assert m.registry.counter("rpcs_deduplicated").value == 1
            # a FRESH seq from the same client buffers normally
            server.PushUpdate(servicers[1].build_update(template, seq=8),
                              None)
            assert engine.status()["buffer_depth"] == 2
        finally:
            server._stopping.set()
            server.stop()

    def test_fast_restart_push_server_heals_codec_without_reconnect(
        self, tmp_path
    ):
        """A push server that restarts within its clients' stub retry
        window is never probed via ReadyForTraining (the channel heals
        transparently), so the Ack-3 reset path never runs — and a push
        server is never polled, so _encode_push never consumes
        _session_reset_pending either. Recovery must deliver the codec
        session resets through PushUpdate replies (bare reset markers
        before the first post-recovery aggregation), or every surviving
        client's delta uplink references pre-crash state forever and the
        federation deadlocks at zero progress."""
        m = MetricsLogger(validate=True)
        server, servicers, template = make_sim_fleet(
            2, steps=60, pacing_policy="push:1", max_iters=200,
            wire_codec="delta", client_codec=True,
            save_dir=str(tmp_path), checkpoint_every=0, journal_every=0,
            metrics=m,
        )
        seqs = {1: 0, 2: 0}

        def push(cid):
            seqs[cid] += 1
            agg = server.PushUpdate(
                servicers[cid].build_update(template, seq=seqs[cid]), None
            )
            servicers[cid].apply(agg)
            return agg

        def drive_until(cond, what, timeout=20.0):
            deadline = time.monotonic() + timeout
            while not cond():
                assert time.monotonic() < deadline, f"timed out: {what}"
                push(1)
                push(2)
                time.sleep(0.02)

        try:
            # Normal push rounds until both clients hold live broadcast
            # references (delta codec sessions warmed on both ends).
            drive_until(
                lambda: min(
                    servicers[c]._applied_round for c in (1, 2)
                ) >= 0,
                "clients never applied a pre-crash broadcast",
            )
            # Adopt the crash-recovered process's wire posture in place
            # (restore_from_checkpoint: fresh codec sessions, no push
            # acks/seqs, a session reset owed to every unfinished
            # member). The loopback stubs stay up throughout — no client
            # ever re-presents its token.
            recovery_round = int(server.global_iterations)
            with server._codec_lock:
                server._uplink_dec.reset()
                server._downlink_enc.reset()
            with server._push_lock:
                server._push_acked.clear()
                server._push_sent.clear()
                server._reset_owed = {
                    c.client_id: recovery_round
                    for c in server.federation.get_clients()
                    if not c.finished
                }
            server._push_seen.clear()
            # The next push deltas against a reference this "process"
            # does not hold; the reply must order the session reset even
            # when there is nothing aggregated to send yet.
            applied_before = servicers[1]._applied
            agg = push(1)
            assert agg.reset_session
            if not len(agg.shared.tensors):
                # Bare reset order: sessions dropped, nothing applied.
                assert servicers[1]._applied is applied_before
            # Sessions dropped → uplinks go self-contained → aggregation
            # resumes → replies deliver post-recovery rounds → the acks
            # pop the owed resets. Without reply-delivered resets this
            # loop times out with every update a codec_ref_miss.
            drive_until(
                lambda: min(
                    servicers[c]._applied_round for c in (1, 2)
                ) >= recovery_round and not server._reset_owed,
                "federation never healed past the recovery round",
            )
            # The heal is loud-but-bounded: at most the in-flight stale
            # uplinks miss, then everything decodes again.
            assert m.registry.counter("codec_ref_miss").value <= 4
        finally:
            server._stopping.set()
            server.stop()

    def test_recovery_reset_not_cleared_by_pre_crash_claim(self, tmp_path):
        """The owed session reset must survive a surviving client's
        pre-crash base_round claim: only ``acked`` (clamped to rounds
        THIS process demonstrably sent) clears it. Journal-lagged
        recovery puts the claim at or past the owed round while the
        recovered process has delivered nothing — clearing on the raw
        claim would leave the client's pre-crash codec sessions alive
        (every uplink a ReferenceMismatch, every reply dedup-skipped:
        zero-progress deadlock)."""
        m = MetricsLogger(validate=True)
        server, servicers, template = make_sim_fleet(
            2, steps=60, pacing_policy="push:1", max_iters=200,
            wire_codec="delta", client_codec=True,
            save_dir=str(tmp_path), checkpoint_every=0, journal_every=0,
            metrics=m,
        )
        seqs = {1: 0, 2: 0}

        def push(cid):
            seqs[cid] += 1
            agg = server.PushUpdate(
                servicers[cid].build_update(template, seq=seqs[cid]), None
            )
            servicers[cid].apply(agg)
            return agg

        try:
            deadline = time.monotonic() + 20.0
            while min(servicers[c]._applied_round for c in (1, 2)) < 1:
                assert time.monotonic() < deadline, "fleet never warmed"
                push(1)
                push(2)
                time.sleep(0.02)
            # Recovered-process posture whose journal LAGGED the crash:
            # the owed reset round sits at or below what the surviving
            # clients already applied pre-crash, so their first claims
            # satisfy claimed >= owed while _push_sent is empty.
            owed = int(servicers[1]._applied_round)
            with server._codec_lock:
                server._uplink_dec.reset()
                server._downlink_enc.reset()
            with server._push_lock:
                server._push_acked.clear()
                server._push_sent.clear()
                server._reset_owed = {
                    c.client_id: owed
                    for c in server.federation.get_clients()
                    if not c.finished
                }
            server._push_seen.clear()
            agg = push(1)
            assert agg.reset_session, (
                "a pre-crash claim >= the owed round cleared the reset "
                "before this process delivered anything"
            )
        finally:
            server._stopping.set()
            server.stop()

    def test_relay_refuses_push_paced_root(self):
        """A relay under a push-paced root would silently never be
        driven (the root never polls, the relay never pushes) — the join
        must fail loudly instead."""
        relay = RelayNode(
            relay_id=1, upstream_address="unused:0", min_members=1,
        )
        relay.federation.connect_vocab(1, ("a", "b"), 4.0)

        class _Stub:
            def OfferVocab(self, req, **kw):
                return pb.Ack(code=0)

            def GetGlobalSetup(self, req, timeout=None, **kw):
                return pb.GlobalSetup(
                    vocab=["a", "b"], model_family="avitm",
                    pacing_id="push:4", hyperparams_json="{}",
                )

        relay._fed_stub = _Stub()
        with pytest.raises(ValueError, match="push"):
            relay._upstream_setup()

    def test_push_federation_e2e_with_delta_codec(self, tmp_path):
        """A real-gRPC 3-client federation under push:2 with delta+topk:
        client-initiated rounds complete, every client finishes, the
        final betas are finite, and the per-recipient reply encoding
        keeps the codec sessions consistent (codec_ref_miss == 0)."""
        rng = np.random.default_rng(2)
        words = [f"tok{i:02d}" for i in range(45)]
        corpora = [
            RawCorpus(documents=[
                " ".join(rng.choice(words, size=12)) for _ in range(16)
            ])
            for _ in range(3)
        ]
        metrics = MetricsLogger(validate=True)
        server = FederatedServer(
            min_clients=3, family="avitm", model_kwargs=MODEL_KWARGS,
            max_iters=60, save_dir=str(tmp_path / "server"),
            metrics=metrics, checkpoint_every=0, round_backoff_s=0.05,
            pacing_policy="push:2", wire_codec="delta+topk:0.25",
        )
        addr = server.start("[::]:0")
        clients = [
            Client(
                client_id=c + 1, corpus=corpus, server_address=addr,
                max_features=45, save_dir=str(tmp_path / f"c{c + 1}"),
                metrics=metrics,
            )
            for c, corpus in enumerate(corpora)
        ]
        threads = [
            threading.Thread(target=c.run, daemon=True) for c in clients
        ]
        for t in threads:
            t.start()
        try:
            assert server.wait_done(timeout=600), "push run did not finish"
            for t in threads:
                t.join(timeout=60)
        finally:
            server.stop()
            for c in clients:
                c.shutdown()
        assert server.global_iterations > 0
        assert server.global_betas is not None
        assert np.isfinite(server.global_betas).all()
        for c in clients:
            assert c.stepper.finished and c.results is not None
        aggs = metrics.events("push_aggregated")
        assert aggs and all(e["buffered"] >= 1 for e in aggs)
        assert metrics.registry.counter("codec_ref_miss").value == 0
        assert metrics.registry.counter("push_updates_received").value > 0
        status = server._status()["pacing"]
        assert status["policy"] == "push:2" and status["push"] is True


# ---- /status summary vs ?full=1 (satellite) ---------------------------------

class TestStatusSummary:
    def test_membership_summary_counts_and_top_failing(self):
        fed = Federation(min_clients=1)
        for cid in range(1, 8):
            fed.connect_vocab(cid, (), float(cid))
            fed.connect_ready(cid, f"sim:{cid}")
        for _ in range(2):
            fed.mark_suspect(3, "sim:3", 0, probation_rounds=9)
        fed.mark_suspect(5, "sim:5", 0, probation_rounds=9)
        summary = fed.membership_summary(top_k=1)
        assert summary["total"] == 7
        assert summary["by_status"] == {"active": 5, "suspect": 2}
        assert summary["ready"] == 7 and summary["finished"] == 0
        assert summary["top_failing"] == [
            {"client_id": 3, "consecutive_failures": 2, "reason": "rpc"},
        ]

    def test_status_default_summary_full_roster_behind_flag(self, tmp_path):
        server = FederatedServer(
            min_clients=2, family="avitm", model_kwargs=MODEL_KWARGS,
            ops_port=0, save_dir=str(tmp_path),
        )
        server.start("[::]:0")
        try:
            base = f"http://127.0.0.1:{server.ops_actual_port}"
            for cid in (1, 2, 3):
                server.federation.connect_vocab(cid, (), 5.0)
            with urllib.request.urlopen(base + "/status", timeout=10) as r:
                status = json.loads(r.read())
            assert status["clients"]["total"] == 3
            assert "by_status" in status["clients"]
            assert "top_slowest" in status["stragglers"]
            with urllib.request.urlopen(
                base + "/status?full=1", timeout=10
            ) as r:
                full = json.loads(r.read())
            assert isinstance(full["clients"], list)
            assert len(full["clients"]) == 3
            assert full["stragglers"] == {}
        finally:
            server.stop()


# ---- per-tier wire accounting in summarize/report (satellite) ---------------

class TestWireTiers:
    @staticmethod
    def _stream(tmp_path, node, sent_raw, sent):
        path = tmp_path / f"{node}.jsonl"
        m = MetricsLogger(str(path), node=node)
        m.registry.counter("uncompressed_bytes_sent").inc(sent_raw)
        m.registry.counter("compressed_bytes_sent").inc(sent)
        m.registry.counter("codec_catchup_pushes").inc(3)
        m.snapshot_registry()
        m.close()
        return str(path)

    def test_collect_and_format_wire_tiers(self, tmp_path):
        from gfedntm_tpu.utils.observability import read_metrics

        paths = {
            "server": self._stream(tmp_path, "server", 4000, 1000),
            "relay1": self._stream(tmp_path, "relay1", 9000, 3000),
        }
        node_records = {
            node: read_metrics(path) for node, path in paths.items()
        }
        tiers = collect_wire_tiers(node_records)
        assert tiers["server"]["ratio_sent"] == 4.0
        assert tiers["relay1"]["ratio_sent"] == 3.0
        assert tiers["relay1"]["catchup_pushes"] == 3
        text = format_wire_tiers(tiers)
        assert "relay1" in text and "4.00x" in text

    def test_summarize_cli_renders_tier_table(self, tmp_path, capsys):
        from gfedntm_tpu.cli import run_summarize

        a = self._stream(tmp_path, "server", 8000, 2000)
        b = self._stream(tmp_path, "relay1", 6000, 3000)
        assert run_summarize([a, b]) == 0
        out = capsys.readouterr().out
        assert "wire accounting per tier" in out
        assert "relay1" in out


# ---- relay tier -------------------------------------------------------------

def _topic_corpora(n, docs=16, seed=11):
    rng = np.random.default_rng(seed)
    words = [f"tok{i:02d}" for i in range(45)]
    return [
        RawCorpus(documents=[
            " ".join(rng.choice(words, size=12)) for _ in range(docs)
        ])
        for _ in range(n)
    ]


def _run_flat(tmp_path, corpora, tag, **server_kw):
    server = FederatedServer(
        min_clients=len(corpora), family="avitm",
        model_kwargs=MODEL_KWARGS, max_iters=60,
        save_dir=str(tmp_path / f"{tag}-server"), checkpoint_every=0,
        round_backoff_s=0.05, **server_kw,
    )
    addr = server.start("[::]:0")
    clients = [
        Client(client_id=c + 1, corpus=corpus, server_address=addr,
               max_features=45, save_dir=str(tmp_path / f"{tag}-c{c + 1}"))
        for c, corpus in enumerate(corpora)
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    try:
        assert server.wait_done(timeout=600), f"{tag}: did not finish"
        for t in threads:
            t.join(timeout=60)
    finally:
        server.stop()
        for c in clients:
            c.shutdown()
    return server


def _run_hier(tmp_path, corpora, tag, n_relays=2, metrics=None,
              relay_kw=None, root_kw=None):
    per_shard = len(corpora) // n_relays
    root = FederatedServer(
        min_clients=n_relays, family="avitm", model_kwargs=MODEL_KWARGS,
        max_iters=60, save_dir=str(tmp_path / f"{tag}-root"),
        metrics=metrics, checkpoint_every=0, round_backoff_s=0.05,
        **(root_kw or {}),
    )
    root_addr = root.start("[::]:0")
    relays = [
        RelayNode(
            relay_id=r + 1, upstream_address=root_addr,
            min_members=per_shard, metrics=metrics, **(relay_kw or {}),
        )
        for r in range(n_relays)
    ]
    relay_addrs = [r.start() for r in relays]
    clients = [
        Client(client_id=c + 1, corpus=corpus,
               server_address=relay_addrs[c // per_shard],
               max_features=45,
               save_dir=str(tmp_path / f"{tag}-hc{c + 1}"))
        for c, corpus in enumerate(corpora)
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    try:
        assert root.wait_done(timeout=600), f"{tag}: hier did not finish"
        for t in threads:
            t.join(timeout=60)
        for r in relays:
            assert r.wait_done(timeout=60), f"{tag}: relay did not stop"
    finally:
        root.stop()
        for r in relays:
            r.shutdown()
        for c in clients:
            c.shutdown()
    return root, relays, clients


class TestRelayTier:
    def test_relay_single_shard_e2e(self, tmp_path):
        """One relay terminating 2 clients under a root expecting one
        'client': the federation completes, both leaf clients finish,
        and the relay emitted pre-aggregation telemetry."""
        metrics = MetricsLogger(validate=True)
        root, relays, clients = _run_hier(
            tmp_path, _topic_corpora(2), "single", n_relays=1,
            metrics=metrics,
        )
        assert root.global_betas is not None
        assert np.isfinite(root.global_betas).all()
        for c in clients:
            assert c.stepper.finished and c.results is not None
        pre = metrics.events("relay_preaggregated")
        assert pre and all(e["relay"] == 1 for e in pre)
        assert metrics.events("relay_joined")
        # the pseudo-update weight is the summed member weight
        assert all(e["admitted"] == 2 for e in pre)

    @pytest.mark.slow
    def test_two_relay_betas_match_flat_topology(self, tmp_path):
        """ISSUE 11 acceptance: 2 relays x 2 clients reach betas within
        1e-4 of the flat 4-client run on the same corpora — the EM
        composition of shard-weighted means with summed weights IS the
        flat FedAvg, up to float re-association."""
        corpora = _topic_corpora(4)
        flat = _run_flat(tmp_path, corpora, "flat")
        hier, _relays, _clients = _run_hier(
            tmp_path, corpora, "hier", n_relays=2,
        )
        assert flat.global_betas is not None
        assert hier.global_betas is not None
        delta = float(np.max(np.abs(flat.global_betas - hier.global_betas)))
        assert delta < 1e-4, f"flat vs hierarchical betas differ: {delta}"

    @pytest.mark.slow
    @pytest.mark.chaos
    def test_poisoned_client_contained_behind_relay(self, tmp_path):
        """ISSUE 11 acceptance: the PR 5 poisoned-client chaos with the
        poisoner sitting BEHIND a relay — the relay's own admission gate
        screens it before its mass can reach the root, and the root's
        model stays finite."""
        metrics = MetricsLogger(validate=True)
        injector = FaultInjector(seed=0, metrics=metrics)
        injector.script(
            "TrainStep", kind="corrupt", payload="scale:100",
            times=64, peer="client3",
        )
        root, relays, clients = _run_hier(
            tmp_path, _topic_corpora(3), "poison", n_relays=1,
            metrics=metrics,
            relay_kw=dict(fault_injector=injector, outlier_mad_k=6.0),
        )
        assert root.global_betas is not None
        assert np.isfinite(root.global_betas).all()
        rejections = metrics.events("update_rejected")
        assert rejections and all(e["client"] == 3 for e in rejections)
        for c in clients[:2]:
            assert c.stepper.finished


# ---- the 1k simulated-client loopback smoke (satellite) ---------------------

@pytest.mark.slow
def test_scale_smoke_1k_clients_fixed_fan(tmp_path):
    """1000 simulated loopback clients under push:16: the control plane
    completes its round budget with per-round wire bytes O(B) — about
    two payloads per buffered update, nowhere near the O(N) a sync
    barrier moves — so the scale path cannot silently rot."""
    n, fan, rounds = 1000, 16, 5
    server, servicers, template = make_sim_fleet(
        n, steps=rounds + 2, pacing_policy=f"push:{fan}",
        max_iters=rounds, save_dir=str(tmp_path), checkpoint_every=0,
        journal_every=0, round_backoff_s=0.02,
    )
    order = sorted(servicers)
    i = 0
    while not server.training_done.is_set():
        cid = order[i % len(order)]
        i += 1
        servicer = servicers[cid]
        if servicer.finished:
            continue
        update = servicer.build_update(template)
        agg = server.PushUpdate(update, None)
        server.byte_counter.note(agg, update)
        servicer.apply(agg)
    assert server.wait_done(timeout=300)
    server.stop()
    assert server.global_iterations == rounds
    # wire cost per round is governed by the buffer, not the population:
    # every drained update cost one uplink payload and one reply, plus
    # slack for hold markers and the final stop replies.
    payload = len(
        codec.flatdict_to_bundle(template).SerializeToString()
    )
    per_round = (
        server.byte_counter.sent + server.byte_counter.recv
    ) / rounds
    assert per_round < 8 * fan * payload, (
        f"per-round bytes {per_round:.0f} not O(B) "
        f"(payload {payload}, fan {fan})"
    )
