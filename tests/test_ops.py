"""Pallas fused-decoder kernel parity tests (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gfedntm_tpu.ops.fused_decoder import (
    prodlda_recon_loss,
    prodlda_recon_loss_reference,
)


def make_inputs(b=12, k=7, v=300, seed=0):
    rng = np.random.default_rng(seed)
    theta = jnp.asarray(
        jax.nn.softmax(jnp.asarray(rng.normal(size=(b, k))), axis=-1),
        jnp.float32,
    )
    beta = jnp.asarray(rng.normal(size=(k, v)), jnp.float32)
    x = jnp.asarray(rng.integers(0, 4, size=(b, v)), jnp.float32)
    run_mean = jnp.asarray(rng.normal(size=(v,)) * 0.1, jnp.float32)
    run_var = jnp.asarray(rng.uniform(0.5, 2.0, size=(v,)), jnp.float32)
    return theta, beta, x, run_mean, run_var


@pytest.mark.parametrize("training", [True, False])
@pytest.mark.parametrize(
    "shape", [(12, 7, 300), (8, 16, 128), (5, 3, 515), (16, 50, 1000)]
)
def test_forward_parity(training, shape):
    b, k, v = shape
    theta, beta, x, rm, rv = make_inputs(b, k, v)
    rl_f, mean_f, var_f = prodlda_recon_loss(
        theta, beta, x, rm, rv, None, training, 1e-5, 1e-10, True
    )
    rl_r, mean_r, var_r = prodlda_recon_loss_reference(
        theta, beta, x, rm, rv, None, training
    )
    np.testing.assert_allclose(rl_f, rl_r, rtol=2e-5, atol=2e-4)
    np.testing.assert_allclose(mean_f, mean_r, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(var_f, var_r, rtol=1e-5, atol=1e-6)


def test_forward_parity_with_mask():
    theta, beta, x, rm, rv = make_inputs(10, 5, 260)
    mask = jnp.asarray([1, 1, 1, 0, 1, 1, 0, 1, 1, 1], jnp.float32)
    rl_f, mean_f, var_f = prodlda_recon_loss(
        theta, beta, x, rm, rv, mask, True, 1e-5, 1e-10, True
    )
    rl_r, mean_r, var_r = prodlda_recon_loss_reference(
        theta, beta, x, rm, rv, mask, True
    )
    np.testing.assert_allclose(mean_f, mean_r, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(var_f, var_r, rtol=1e-5, atol=1e-6)
    real = np.asarray(mask) > 0
    np.testing.assert_allclose(
        np.asarray(rl_f)[real], np.asarray(rl_r)[real], rtol=2e-5, atol=2e-4
    )
    assert np.isfinite(np.asarray(rl_f)).all()


def test_all_masked_rows_are_finite():
    theta, beta, x, rm, rv = make_inputs(8, 4, 140)
    mask = jnp.zeros((8,), jnp.float32)
    rl, mean, var = prodlda_recon_loss(
        theta, beta, x, rm, rv, mask, True, 1e-5, 1e-10, True
    )
    assert np.isfinite(np.asarray(rl)).all()


def assert_grad_parity(theta, beta, x, rm, rv, mask=None, training=True,
                       max_rel=None):
    """Compare fused-vs-reference grads of sum(rl [* mask]).

    ``max_rel=None`` uses elementwise allclose (1e-4); a float switches to
    a max-abs-relative-to-peak criterion (the multi-tile regime's grads
    span orders of magnitude, making elementwise rtol too strict)."""
    msum = (lambda rl: jnp.sum(rl * mask)) if mask is not None else jnp.sum

    def loss_fused(th, be):
        rl, _, _ = prodlda_recon_loss(
            th, be, x, rm, rv, mask, training, 1e-5, 1e-10, True
        )
        return msum(rl)

    def loss_ref(th, be):
        rl, _, _ = prodlda_recon_loss_reference(
            th, be, x, rm, rv, mask, training
        )
        return msum(rl)

    gf = jax.grad(loss_fused, argnums=(0, 1))(theta, beta)
    gr = jax.grad(loss_ref, argnums=(0, 1))(theta, beta)
    for a, c in zip(gf, gr):
        if max_rel is None:
            np.testing.assert_allclose(a, c, rtol=1e-4, atol=1e-4)
        else:
            scale = float(jnp.max(jnp.abs(c))) + 1e-9
            assert float(jnp.max(jnp.abs(a - c))) / scale < max_rel


@pytest.mark.parametrize("training", [True, False])
def test_gradient_parity(training):
    theta, beta, x, rm, rv = make_inputs(10, 6, 257)
    assert_grad_parity(theta, beta, x, rm, rv, training=training)


def test_gradient_parity_with_mask():
    theta, beta, x, rm, rv = make_inputs(9, 5, 200)
    mask = jnp.asarray([1, 1, 0, 1, 1, 1, 0, 1, 1], jnp.float32)
    assert_grad_parity(theta, beta, x, rm, rv, mask=mask)


def test_gradient_parity_weighted_cotangent():
    """Non-uniform rl cotangent: the streaming backward folds the row-dot
    into the forward pass (cotangent-independent by construction) and
    applies the general cotangent only in the grads pass — a weighted loss
    pins that the split is correct for g != 1."""
    theta, beta, x, rm, rv = make_inputs(10, 6, 300)
    w = jnp.asarray(np.linspace(0.1, 2.0, 10), jnp.float32)

    def loss_fused(th, be):
        rl, _, _ = prodlda_recon_loss(
            th, be, x, rm, rv, None, True, 1e-5, 1e-10, True
        )
        return jnp.sum(rl * w)

    def loss_ref(th, be):
        rl, _, _ = prodlda_recon_loss_reference(th, be, x, rm, rv, None, True)
        return jnp.sum(rl * w)

    gf_t, gf_b = jax.grad(loss_fused, argnums=(0, 1))(theta, beta)
    gr_t, gr_b = jax.grad(loss_ref, argnums=(0, 1))(theta, beta)
    np.testing.assert_allclose(gf_t, gr_t, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gf_b, gr_b, rtol=1e-4, atol=1e-4)


def test_stats_have_no_gradient_path():
    theta, beta, x, rm, rv = make_inputs(8, 4, 130)

    def mean_sum(th):
        _, mean, _ = prodlda_recon_loss(
            th, beta, x, rm, rv, None, True, 1e-5, 1e-10, True
        )
        return jnp.sum(mean)

    g = jax.grad(mean_sum)(theta)
    np.testing.assert_allclose(g, jnp.zeros_like(g))


def test_jit_compatible():
    theta, beta, x, rm, rv = make_inputs(8, 4, 256)

    @jax.jit
    def f(th, be, xx):
        rl, _, _ = prodlda_recon_loss(
            th, be, xx, rm, rv, None, True, 1e-5, 1e-10, True
        )
        return rl

    rl = f(theta, beta, x)
    rl_r, _, _ = prodlda_recon_loss_reference(
        theta, beta, x, rm, rv, None, True
    )
    np.testing.assert_allclose(rl, rl_r, rtol=2e-5, atol=2e-4)


@pytest.mark.slow
class TestFusedTrainingPath:
    """The fused kernel dropped into the real training step must reproduce
    the unfused trajectory (same rng folds, same BN running-stat updates)."""

    def _train(self, fused: bool, seed=0):
        from gfedntm_tpu.data.datasets import BowDataset
        from gfedntm_tpu.models.avitm import AVITM

        rng = np.random.default_rng(3)
        V, docs = 150, 24
        X = rng.integers(0, 3, size=(docs, V)).astype(np.float32)
        data = BowDataset(X=X, idx2token={i: f"wd{i}" for i in range(V)})
        model = AVITM(
            input_size=V, n_components=4, hidden_sizes=(16, 16),
            batch_size=8, num_epochs=2, seed=seed, fused_decoder=fused,
        )
        model.fit(data)
        return model

    def test_fused_matches_unfused_training(self):
        m_fused = self._train(True)
        m_plain = self._train(False)
        np.testing.assert_allclose(
            np.asarray(m_fused.params["beta"]),
            np.asarray(m_plain.params["beta"]),
            rtol=5e-4, atol=5e-4,
        )
        bn_f = m_fused.batch_stats["beta_batchnorm"]
        bn_p = m_plain.batch_stats["beta_batchnorm"]
        np.testing.assert_allclose(
            np.asarray(bn_f["running_mean"]),
            np.asarray(bn_p["running_mean"]), rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(bn_f["running_var"]),
            np.asarray(bn_p["running_var"]), rtol=1e-4, atol=1e-5,
        )
        assert int(bn_f["num_batches_tracked"]) == int(
            bn_p["num_batches_tracked"]
        )

    def test_fused_federated_program(self):
        from gfedntm_tpu.data.datasets import BowDataset
        from gfedntm_tpu.federated.trainer import FederatedTrainer
        from gfedntm_tpu.models.avitm import AVITM

        rng = np.random.default_rng(5)
        V, docs, C = 130, 16, 2
        datasets = [
            BowDataset(
                X=rng.integers(0, 3, size=(docs, V)).astype(np.float32),
                idx2token={i: f"wd{i}" for i in range(V)},
            )
            for _ in range(C)
        ]
        results = {}
        for fused in (True, False):
            template = AVITM(
                input_size=V, n_components=3, hidden_sizes=(8, 8),
                batch_size=8, num_epochs=1, seed=0, fused_decoder=fused,
            )
            trainer = FederatedTrainer(template, n_clients=C)
            results[fused] = trainer.fit(datasets)
        np.testing.assert_allclose(
            np.asarray(results[True].client_params["beta"]),
            np.asarray(results[False].client_params["beta"]),
            rtol=5e-4, atol=5e-4,
        )
        assert np.isfinite(results[True].losses).all()


class TestTilePicker:
    """Round-3 fix: V pads up to a multiple of the tile so big vocabularies
    never degenerate to 128-wide grid steps (VERDICT r2 Weak #1 follow-on:
    V=50000 used to pick tile 128 -> 391 sequential tiles)."""

    def test_small_v_single_tile(self):
        from gfedntm_tpu.ops.fused_decoder import _pick_tile_v

        assert _pick_tile_v(300) == (384, 384)
        assert _pick_tile_v(2048) == (2048, 2048)
        assert _pick_tile_v(64) == (128, 128)

    def test_large_v_pads_to_tile(self):
        from gfedntm_tpu.ops.fused_decoder import _pick_tile_v

        assert _pick_tile_v(50_000) == (2048, 51_200)
        assert _pick_tile_v(100_000) == (2048, 100_352)
        assert _pick_tile_v(16_384) == (2048, 16_384)

    def test_multi_tile_parity_with_padding(self):
        # V=5000 pads to 5120 under the new picker (was exact before):
        # exercises n_tiles > 1 plus a padded tail in interpret mode.
        theta, beta, x, rm, rv = make_inputs(12, 7, 5000)
        rl_f, mean_f, var_f = prodlda_recon_loss(
            theta, beta, x, rm, rv, None, True, 1e-5, 1e-10, True
        )
        rl_r, mean_r, var_r = prodlda_recon_loss_reference(
            theta, beta, x, rm, rv, None, True
        )
        np.testing.assert_allclose(rl_f, rl_r, rtol=2e-5, atol=2e-3)
        np.testing.assert_allclose(mean_f, mean_r, rtol=1e-5, atol=1e-5)

    def test_multi_tile_gradient_parity(self):
        # Pins the streaming backward's cross-tile machinery (rd/g_theta
        # accumulator init at j==0, per-tile g_beta blocks, padded tail):
        # every other grad test resolves to a single V tile.
        theta, beta, x, rm, rv = make_inputs(10, 6, 5000)
        mask = jnp.asarray([1] * 8 + [0] * 2, jnp.float32)
        assert_grad_parity(theta, beta, x, rm, rv, mask=mask, max_rel=2e-4)


    def test_vmem_frontier_clamp_scales_with_batch(self, monkeypatch):
        """Round-4 fix: the backward kernel's scoped-VMEM working set
        scales with B x TILE_V; b_pad*tile must stay within the measured
        2^19 frontier (the soak crashed compiling B=256 x tile=4096:
        19.17M > the 16M Mosaic limit)."""
        from gfedntm_tpu.ops.fused_decoder import (
            _VMEM_TILE_ELEMS,
            _pick_tile_v,
        )

        monkeypatch.delenv("GFEDNTM_FUSED_TILE_V", raising=False)
        monkeypatch.delenv("GFEDNTM_FUSED_TILE_UNCLAMPED", raising=False)
        # default geometry unchanged at small batch
        assert _pick_tile_v(100_000, 64) == (2048, 100_352)
        # large batch narrows the auto tile to stay inside the frontier
        tile_b256, _ = _pick_tile_v(100_000, 256)
        assert tile_b256 * 256 <= _VMEM_TILE_ELEMS
        # past-frontier batches keep the one-lane floor (shape validity)
        # and warn that no tile width is known-safe
        import logging as _logging

        from gfedntm_tpu.ops import fused_decoder as fd

        fd._CLAMP_WARNED.clear()
        records: list = []
        handler = _logging.Handler()
        handler.emit = records.append
        logger = _logging.getLogger("gfedntm_tpu.ops.fused_decoder")
        logger.addHandler(handler)
        try:
            assert _pick_tile_v(100_000, 8192)[0] == 128
        finally:
            logger.removeHandler(handler)
        assert any("frontier" in r.getMessage() for r in records)

    def test_small_k_widens_default_tile(self, monkeypatch):
        """Round-4 TPU tile sweep: at V=50k B=64 the 2048 default tile only
        broke even (0.97x unfused) while the frontier-wide 8192 tile ran
        1.63x — so small-K models (the regime the frontier was measured
        in, K=50) default to frontier-wide tiles. Large K keeps the
        proven 2048 cap; the b_pad*tile frontier still binds."""
        from gfedntm_tpu.ops.fused_decoder import (
            _VMEM_TILE_ELEMS,
            _pick_tile_v,
            resolve_tile_v,
        )

        monkeypatch.delenv("GFEDNTM_FUSED_TILE_V", raising=False)
        monkeypatch.delenv("GFEDNTM_FUSED_TILE_UNCLAMPED", raising=False)
        # K=50 (k_pad=56): widened to the frontier width at B=64
        assert _pick_tile_v(50_000, 64, 56) == (8192, 57_344)
        assert resolve_tile_v(50_000, 64, 50) == 8192
        # the frontier still narrows the tile as batch grows
        tile_b256, _ = _pick_tile_v(50_000, 256, 56)
        assert tile_b256 == 2048 and tile_b256 * 256 <= _VMEM_TILE_ELEMS
        # past the measured regime (k_pad > 64): conservative cap
        assert _pick_tile_v(50_000, 64, 128)[0] == 2048
        assert _pick_tile_v(50_000, 64, 256)[0] == 2048
        # k omitted: legacy conservative resolution is unchanged
        assert _pick_tile_v(50_000, 64)[0] == 2048

    def test_override_clamped_to_frontier(self, monkeypatch):
        """An operator tile request past the frontier is clamped (not
        honored into a guaranteed compile crash), and the probe-only
        bypass restores the raw geometry."""
        from gfedntm_tpu.ops.fused_decoder import (
            _pick_tile_v,
            resolve_tile_v,
        )

        monkeypatch.setenv("GFEDNTM_FUSED_TILE_V", "4096")
        assert _pick_tile_v(100_000, 256)[0] == 2048  # clamped
        assert _pick_tile_v(100_000, 64)[0] == 4096   # within frontier
        assert resolve_tile_v(100_000, 256) == 2048
        assert resolve_tile_v(100_000, 60) == 4096    # b_pad=64 rule shared
        monkeypatch.setenv("GFEDNTM_FUSED_TILE_UNCLAMPED", "1")
        assert _pick_tile_v(100_000, 256)[0] == 4096  # probe bypass

    def test_soak_error_rows_keep_geometry(self, monkeypatch):
        """bench_fused_largev must record a failing case (with its
        resolved tile) instead of losing the artifact — the round-4 soak
        died at its last sweep case and dropped every measured row."""
        import bench as bench_mod

        def boom(V, B, interpret, storage="float32"):
            raise RuntimeError("mosaic scoped vmem")

        monkeypatch.setattr(bench_mod, "_fused_case", boom)
        table = bench_mod.bench_fused_largev("cpu")
        row = table["V2048_B64"]
        assert row["parity"] is False
        assert "mosaic scoped vmem" in row["error"]
        assert row["tile_v"] == 2048

    @pytest.mark.parametrize("tile", ["256", "512"])
    def test_tile_override_parity_fwd_and_grad(self, tile, monkeypatch):
        """The GFEDNTM_FUSED_TILE_V sweep configurations must be
        parity-correct, not just the default geometry — the soak script
        sweeps the knob on real TPU and an untested tiling would waste
        chip time on a latent blockspec bug. Small overrides exercise the
        same parametrized geometry (incl. a padded tail: V=900 -> 4x256
        or 2x512) cheaply in interpret mode."""
        monkeypatch.setenv("GFEDNTM_FUSED_TILE_V", tile)
        theta, beta, x, rm, rv = make_inputs(9, 5, 900)
        rl_f, mean_f, _ = prodlda_recon_loss(
            theta, beta, x, rm, rv, None, True, 1e-5, 1e-10, True
        )
        rl_r, mean_r, _ = prodlda_recon_loss_reference(
            theta, beta, x, rm, rv, None, True
        )
        np.testing.assert_allclose(rl_f, rl_r, rtol=2e-5, atol=2e-3)
        np.testing.assert_allclose(mean_f, mean_r, rtol=1e-5, atol=1e-5)
        assert_grad_parity(theta, beta, x, rm, rv, max_rel=2e-4)


class TestFailSafe:
    """`fused_decoder="auto"` must never crash a run the unfused XLA loss
    could complete (VERDICT r2 task 1)."""

    def test_kernel_health_caches_per_backend_and_tile(self):
        from gfedntm_tpu.ops import fused_decoder as fd

        # kernel_health probes the caller's geometry class (default
        # b=8/k=8 resolves the small-K widened tiling) and keys the cache
        # on backend + padded geometry — mirror that resolution here.
        tile_v, _ = fd._pick_tile_v(1 << 30, 8, 8)
        key = f"cpu:b8k8tile{tile_v}sfloat32"
        fd._KERNEL_HEALTH.pop(key, None)
        ok, err = fd.kernel_health("cpu")
        assert ok and err == ""
        assert fd._KERNEL_HEALTH[key] == (True, "")
        # A poisoned cache entry is honoured without re-probing.
        fd._KERNEL_HEALTH[key] = (False, "boom")
        assert fd.kernel_health("cpu") == (False, "boom")
        fd._KERNEL_HEALTH.pop(key, None)

    def test_kernel_health_malformed_override_degrades_not_raises(
        self, monkeypatch
    ):
        """A typo'd GFEDNTM_FUSED_TILE_V (e.g. left over from a soak sweep)
        must return (False, msg) so 'auto' falls back to unfused — never
        raise out of kernel_health."""
        from gfedntm_tpu.ops import fused_decoder as fd

        monkeypatch.setenv("GFEDNTM_FUSED_TILE_V", "2048,")
        ok, err = fd.kernel_health("cpu")
        assert not ok and "GFEDNTM_FUSED_TILE_V" in err

    def test_kernel_health_probe_stays_multi_tile_under_override(self,
                                                                 monkeypatch):
        """ADVICE r3: an override >= the old fixed probe V must not turn
        the probe single-tile — the probe geometry tracks the knob."""
        from gfedntm_tpu.ops import fused_decoder as fd

        monkeypatch.setenv("GFEDNTM_FUSED_TILE_V", "8192")
        tile_v, _ = fd._pick_tile_v(1 << 30, 8, 8)
        assert tile_v == 8192
        key = f"cpu:b8k8tile{tile_v}sfloat32"
        fd._KERNEL_HEALTH.pop(key, None)
        ok, err = fd.kernel_health("cpu")
        assert ok and err == ""
        assert key in fd._KERNEL_HEALTH  # keyed on the resolved tile
        fd._KERNEL_HEALTH.pop(key, None)

    def test_resolve_fused_auto_off_tpu(self):
        from gfedntm_tpu.models.avitm import AVITM

        model = AVITM(
            input_size=20_000, n_components=5, hidden_sizes=(16,),
            batch_size=8, num_epochs=1, seed=0,
        )
        # CPU backend: auto resolves False regardless of vocabulary size.
        assert model.module.fused_decoder is False

    def test_fit_falls_back_when_fused_path_raises(self):
        from gfedntm_tpu.data.datasets import BowDataset
        from gfedntm_tpu.models.avitm import AVITM

        rng = np.random.default_rng(0)
        X = rng.integers(0, 3, size=(40, 60)).astype(np.float32)
        ds = BowDataset(X=X, idx2token={i: f"w{i}" for i in range(60)})
        model = AVITM(
            input_size=60, n_components=4, hidden_sizes=(16,),
            batch_size=16, num_epochs=1, seed=0, fused_decoder=True,
        )
        assert model.module.fused_decoder is True

        calls = {"n": 0}
        real_fn = model._train_epoch_fn

        def exploding(*args, **kwargs):
            calls["n"] += 1
            raise RuntimeError("Mosaic lowering failed (simulated)")

        model._train_epoch_fn = exploding
        model.fit(ds)  # must complete on the unfused path, not raise
        assert calls["n"] == 1
        assert model.fused_decoder is False
        assert model.module.fused_decoder is False
        assert np.isfinite(model.epoch_losses).all()
        del real_fn


class TestVShardedFused:
    """V-sharded fused loss under shard_map (VERDICT r2 task 5): each
    device streams its local V shard through the Pallas kernel; only
    [B, 1] online-softmax merges + the [B] loss psum cross the model axis."""

    def _mesh(self, shape, names):
        devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
        from jax.sharding import Mesh

        return Mesh(devs, names)

    def _run(self, mesh, data_axis, model_axis, b=16, k=5, v=512, seed=0):
        from functools import partial

        from jax.sharding import PartitionSpec as P

        from gfedntm_tpu.ops.fused_decoder import prodlda_recon_loss_vsharded

        theta, beta, x, rm, rv = make_inputs(b, k, v, seed)
        mask = jnp.asarray(
            (np.random.default_rng(seed).random(b) > 0.2), jnp.float32
        )

        from gfedntm_tpu.parallel.mesh import shard_map_compat

        sharded = jax.jit(
            shard_map_compat(
                partial(
                    prodlda_recon_loss_vsharded,
                    model_axis=model_axis, data_axis=data_axis,
                    training=True, interpret=True,
                ),
                mesh,
                in_specs=(
                    P(data_axis, None), P(None, model_axis),
                    P(data_axis, model_axis), P(model_axis), P(model_axis),
                    P(data_axis),
                ),
                out_specs=(
                    P(data_axis), P(model_axis), P(model_axis)
                ),
                check=False,
            )
        )
        return sharded(theta, beta, x, rm, rv, mask), (theta, beta, x, rm, rv, mask)

    @pytest.mark.parametrize("data_axis,shape,names", [
        (None, (8,), ("model",)),
        ("data", (2, 4), ("data", "model")),
    ])
    def test_forward_parity(self, data_axis, shape, names):
        mesh = self._mesh(shape, names)
        (rl, mean, var), (theta, beta, x, rm, rv, mask) = self._run(
            mesh, data_axis, "model"
        )
        rl_r, mean_r, var_r = prodlda_recon_loss_reference(
            theta, beta, x, rm, rv, mask, True
        )
        real = np.asarray(mask) > 0
        np.testing.assert_allclose(
            np.asarray(rl)[real], np.asarray(rl_r)[real],
            rtol=2e-5, atol=2e-3,
        )
        np.testing.assert_allclose(mean, mean_r, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(var, var_r, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("data_axis,shape,names", [
        (None, (4,), ("model",)),
        ("data", (2, 2), ("data", "model")),
    ])
    @pytest.mark.slow
    def test_gradient_parity(self, data_axis, shape, names):
        from functools import partial

        from jax.sharding import PartitionSpec as P

        from gfedntm_tpu.ops.fused_decoder import prodlda_recon_loss_vsharded

        mesh = self._mesh(shape, names)
        b, k, v = 12, 5, 384
        theta, beta, x, rm, rv = make_inputs(b, k, v)
        mask = jnp.asarray([1.0] * 10 + [0.0] * 2, jnp.float32)

        from gfedntm_tpu.parallel.mesh import shard_map_compat

        inner = shard_map_compat(
            partial(
                prodlda_recon_loss_vsharded,
                model_axis="model", data_axis=data_axis,
                training=True, interpret=True,
            ),
            mesh,
            in_specs=(
                P(data_axis, None), P(None, "model"),
                P(data_axis, "model"), P("model"), P("model"), P(data_axis),
            ),
            out_specs=(P(data_axis), P("model"), P("model")),
            check=False,
        )

        def loss_sharded(th, bt):
            rl, _, _ = inner(th, bt, x, rm, rv, mask)
            return jnp.sum(rl * mask)

        def loss_ref(th, bt):
            rl, _, _ = prodlda_recon_loss_reference(
                th, bt, x, rm, rv, mask, True
            )
            return jnp.sum(rl * mask)

        g_s = jax.grad(loss_sharded, argnums=(0, 1))(theta, beta)
        g_r = jax.grad(loss_ref, argnums=(0, 1))(theta, beta)
        for a, c in zip(g_s, g_r):
            scale = float(jnp.max(jnp.abs(c))) + 1e-9
            assert float(jnp.max(jnp.abs(a - c))) / scale < 5e-4


class TestBf16Storage:
    """bf16 storage for beta/x (VERDICT r4 #3): HBM traffic halves while
    every accumulation stays f32. Parity criterion: the kernel on
    bf16-stored operands must match the f32 reference evaluated at the
    SAME quantized point to f32-accumulation precision — i.e. storage
    quantization is the ONLY difference. (Interpret mode on CPU.)"""

    @staticmethod
    def _quantized(beta, x):
        q = lambda a: a.astype(jnp.bfloat16).astype(jnp.float32)
        return q(beta), q(x)

    @pytest.mark.parametrize("shape", [(12, 7, 300), (5, 3, 515)])
    def test_forward_matches_reference_at_quantized_point(self, shape):
        b, k, v = shape
        theta, beta, x, rm, rv = make_inputs(b, k, v)
        rl_f, mean_f, var_f = prodlda_recon_loss(
            theta, beta, x, rm, rv, None, True, 1e-5, 1e-10, True, "bfloat16"
        )
        beta_q, x_q = self._quantized(beta, x)
        rl_r, mean_r, var_r = prodlda_recon_loss_reference(
            theta, beta_q, x_q, rm, rv, None, True
        )
        np.testing.assert_allclose(rl_f, rl_r, rtol=2e-5, atol=2e-4)
        np.testing.assert_allclose(mean_f, mean_r, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(var_f, var_r, rtol=1e-5, atol=1e-6)

    def test_gradient_matches_reference_at_quantized_point(self):
        theta, beta, x, rm, rv = make_inputs(10, 6, 257)
        beta_q, x_q = self._quantized(beta, x)

        def loss_fused(th, be):
            rl, _, _ = prodlda_recon_loss(
                th, be, x, rm, rv, None, True, 1e-5, 1e-10, True, "bfloat16"
            )
            return jnp.sum(rl)

        def loss_ref(th, be):
            rl, _, _ = prodlda_recon_loss_reference(
                th, be, x_q, rm, rv, None, True
            )
            return jnp.sum(rl)

        gf = jax.grad(loss_fused, argnums=(0, 1))(theta, beta)
        # Reference gradient AT the quantized beta (the fused kernel
        # differentiates through the quantized point; d(quantize)/d(beta)
        # is treated as identity, standard mixed-precision semantics).
        gr = jax.grad(loss_ref, argnums=(0, 1))(theta, beta_q)
        for a, c in zip(gf, gr):
            np.testing.assert_allclose(a, c, rtol=1e-4, atol=1e-4)

    def test_bow_counts_are_exact_in_bf16(self):
        """Integer BoW counts < 256 are representable exactly in bf16
        (8-bit mantissa), so x quantization is lossless in practice."""
        x = jnp.asarray(np.arange(256), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(x.astype(jnp.bfloat16).astype(jnp.float32)),
            np.asarray(x),
        )

    def test_bf16_geometry_pads_to_16(self):
        from gfedntm_tpu.ops.fused_decoder import _pad_geometry

        b_pad, k_pad, _, _ = _pad_geometry(12, 7, 300, "bfloat16")
        assert b_pad % 16 == 0 and k_pad % 16 == 0
        b_pad, k_pad, _, _ = _pad_geometry(12, 7, 300, "float32")
        assert b_pad == 16 and k_pad == 8

    def test_masked_bf16_parity(self):
        theta, beta, x, rm, rv = make_inputs(10, 5, 260)
        mask = jnp.asarray([1, 1, 1, 0, 1, 1, 0, 1, 1, 1], jnp.float32)
        beta_q, x_q = self._quantized(beta, x)
        rl_f, mean_f, var_f = prodlda_recon_loss(
            theta, beta, x, rm, rv, mask, True, 1e-5, 1e-10, True, "bfloat16"
        )
        rl_r, mean_r, var_r = prodlda_recon_loss_reference(
            theta, beta_q, x_q, rm, rv, mask, True
        )
        real = np.asarray(mask) > 0
        np.testing.assert_allclose(
            np.asarray(rl_f)[real], np.asarray(rl_r)[real],
            rtol=2e-5, atol=2e-4,
        )
        np.testing.assert_allclose(mean_f, mean_r, rtol=1e-5, atol=1e-6)

    def test_invalid_storage_dtype_raises(self):
        theta, beta, x, rm, rv = make_inputs(8, 4, 130)
        with pytest.raises(ValueError):
            prodlda_recon_loss(
                theta, beta, x, rm, rv, None, True, 1e-5, 1e-10, True,
                "float16",
            )


class TestBf16FederatedPath:
    """compute_dtype='bfloat16' + the fused kernel through the WHOLE
    federated trainer (interpret mode): the bf16-storage path must match
    the unfused bf16 trajectory — pins the module-boundary dtype flow
    (cotangents, BN stats) the kernel-level tests can't see."""

    def test_bf16_fused_federated_matches_unfused(self):
        from gfedntm_tpu.data.datasets import BowDataset
        from gfedntm_tpu.federated.trainer import FederatedTrainer
        from gfedntm_tpu.models.avitm import AVITM

        rng = np.random.default_rng(7)
        V, docs, C = 130, 16, 2
        datasets = [
            BowDataset(
                X=rng.integers(0, 3, size=(docs, V)).astype(np.float32),
                idx2token={i: f"wd{i}" for i in range(V)},
            )
            for _ in range(C)
        ]
        results = {}
        for fused in (True, False):
            template = AVITM(
                input_size=V, n_components=3, hidden_sizes=(8, 8),
                batch_size=8, num_epochs=1, seed=0, fused_decoder=fused,
                compute_dtype="bfloat16",
            )
            trainer = FederatedTrainer(template, n_clients=C)
            results[fused] = trainer.fit(datasets)
        # bf16 matmuls dominate the noise floor; the fused/unfused delta
        # must sit inside it (storage quantization = the same bf16 cast
        # the unfused path's matmuls already apply to their inputs).
        np.testing.assert_allclose(
            np.asarray(results[True].client_params["beta"]),
            np.asarray(results[False].client_params["beta"]),
            rtol=5e-2, atol=5e-2,
        )
        assert np.isfinite(results[True].losses).all()
        assert np.isfinite(results[False].losses).all()


class TestVShardedBf16Storage:
    """bf16 storage through the V-sharded fused path (rows-replicated
    Pallas branch): parity at the quantized point, like the
    single-device bf16 tests — on the 8-virtual-device CPU mesh."""

    @pytest.mark.slow
    def test_forward_and_grad_parity_quantized_point(self):
        from functools import partial

        from jax.sharding import Mesh, PartitionSpec as P

        from gfedntm_tpu.ops.fused_decoder import prodlda_recon_loss_vsharded

        mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
        b, k, v = 12, 5, 384
        theta, beta, x, rm, rv = make_inputs(b, k, v)
        mask = jnp.ones((b,), jnp.float32)
        q = lambda a: a.astype(jnp.bfloat16).astype(jnp.float32)

        from gfedntm_tpu.parallel.mesh import shard_map_compat

        inner = shard_map_compat(
            partial(
                prodlda_recon_loss_vsharded,
                model_axis="model", data_axis=None,
                training=True, interpret=True, storage_dtype="bfloat16",
            ),
            mesh,
            in_specs=(
                P(None, None), P(None, "model"), P(None, "model"),
                P("model"), P("model"), P(None),
            ),
            out_specs=(P(None), P("model"), P("model")),
            check=False,
        )

        def loss_sharded(th, bt):
            rl, _, _ = inner(th, bt, x, rm, rv, mask)
            return jnp.sum(rl * mask)

        def loss_ref(th, bt):
            rl, _, _ = prodlda_recon_loss_reference(
                th, bt, q(x), rm, rv, mask, True
            )
            return jnp.sum(rl * mask)

        lf, gf = jax.value_and_grad(loss_sharded, argnums=(0, 1))(
            theta, beta
        )
        lr, gr = jax.value_and_grad(loss_ref, argnums=(0, 1))(
            theta, q(beta)
        )
        assert abs(float(lf) - float(lr)) / abs(float(lr)) < 1e-4
        for a, c in zip(gf, gr):
            np.testing.assert_allclose(a, c, rtol=2e-4, atol=2e-4)


class TestLargeVBlockSpecRegression:
    """BENCH_r02 ``fused_largev_error`` regression (ISSUE 6 satellite): the
    round-2 kernel emitted the online-softmax accumulators as a
    ``[B, n_tiles]`` partials array with ``(B, 1)`` blocks, which Mosaic
    rejects whenever ``n_tiles > 1`` ("block shape (64, 1), array shape
    (64, 8)" at B=64, 8 V-tiles). The redesigned kernels keep m/s as full
    ``(B_pad, 1)`` arrays; these tests pin (a) the static Mosaic legality
    of every block spec at the failing geometry and (b) interpret-mode
    parity through the exact multi-tile grid that produced the error."""

    R02_B, R02_K = 64, 50  # the bench soak's failing batch/topic geometry

    def test_blockspecs_mosaic_legal_at_r02_geometry(self, monkeypatch):
        from gfedntm_tpu.ops.fused_decoder import (
            assert_mosaic_legal,
            pass_block_geometry,
            resolve_tile_v,
        )

        monkeypatch.delenv("GFEDNTM_FUSED_TILE_V", raising=False)
        # The literal r02 failing config (V=16384, B=64: 8 tiles of 2048
        # under the round-2 cap) plus the full soak sweep grid.
        for v in (16384, 50_000, 100_000):
            for b in (self.R02_B, 256):
                for storage in ("float32", "bfloat16"):
                    assert_mosaic_legal(b, self.R02_K, v, storage)
        # The specific shape from the recorded error: 8 V-tiles at B=64.
        monkeypatch.setenv("GFEDNTM_FUSED_TILE_V", "2048")
        assert resolve_tile_v(16384, self.R02_B, self.R02_K) == 2048
        geom = pass_block_geometry(self.R02_B, self.R02_K, 16384)
        assert_mosaic_legal(self.R02_B, self.R02_K, 16384)
        # The r02 failure was outputs[2] of _stats_kernel (the softmax
        # max accumulator): it must be a full-array block, never a
        # 1-lane slice of an [B, n_tiles] partials array.
        block, array = geom["stats.m"]
        assert block == array == (64, 1)

    def test_stats_outputs_are_full_array_accumulators(self):
        from gfedntm_tpu.ops.fused_decoder import pass_block_geometry

        for name in ("stats.m", "stats.s", "loss.out", "loss.rd"):
            block, array = pass_block_geometry(
                self.R02_B, self.R02_K, 100_000
            )[name]
            assert block == array, (name, block, array)

    def test_interpret_parity_at_r02_multi_tile_grid(self, monkeypatch):
        # n_tiles=8 at B=64 — the exact grid class of the recorded error,
        # shrunk via the tile override so interpret mode stays fast while
        # the multi-tile accumulator path (the code the bug lived in) is
        # the one that runs.
        monkeypatch.setenv("GFEDNTM_FUSED_TILE_V", "128")
        v = 8 * 128
        theta, beta, x, rm, rv = make_inputs(self.R02_B, self.R02_K, v)

        def loss_fused(th, bt):
            rl, _, _ = prodlda_recon_loss(
                th, bt, x, rm, rv, None, True, 1e-5, 1e-10, True
            )
            return jnp.sum(rl)

        def loss_ref(th, bt):
            rl, _, _ = prodlda_recon_loss_reference(
                th, bt, x, rm, rv, None, True
            )
            return jnp.sum(rl)

        lf, gf = jax.value_and_grad(loss_fused, argnums=(0, 1))(theta, beta)
        lr, gr = jax.value_and_grad(loss_ref, argnums=(0, 1))(theta, beta)
        assert abs(float(lf) - float(lr)) / abs(float(lr)) < 1e-4
        # Grad tolerance is loose-ish: fused-vs-unfused f32 differences at
        # B=64 x V=1024 are summation-order noise (see bench._fused_case's
        # f64-oracle criterion); a broken multi-tile accumulator is off by
        # orders of magnitude, not 1e-3 relative.
        for a, b_ in zip(gf, gr):
            scale = float(np.max(np.abs(np.asarray(b_))))
            np.testing.assert_allclose(
                np.asarray(a) / scale, np.asarray(b_) / scale,
                atol=2e-5,
            )
