"""Pallas fused-decoder kernel parity tests (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gfedntm_tpu.ops.fused_decoder import (
    prodlda_recon_loss,
    prodlda_recon_loss_reference,
)


def make_inputs(b=12, k=7, v=300, seed=0):
    rng = np.random.default_rng(seed)
    theta = jnp.asarray(
        jax.nn.softmax(jnp.asarray(rng.normal(size=(b, k))), axis=-1),
        jnp.float32,
    )
    beta = jnp.asarray(rng.normal(size=(k, v)), jnp.float32)
    x = jnp.asarray(rng.integers(0, 4, size=(b, v)), jnp.float32)
    run_mean = jnp.asarray(rng.normal(size=(v,)) * 0.1, jnp.float32)
    run_var = jnp.asarray(rng.uniform(0.5, 2.0, size=(v,)), jnp.float32)
    return theta, beta, x, run_mean, run_var


@pytest.mark.parametrize("training", [True, False])
@pytest.mark.parametrize(
    "shape", [(12, 7, 300), (8, 16, 128), (5, 3, 515), (16, 50, 1000)]
)
def test_forward_parity(training, shape):
    b, k, v = shape
    theta, beta, x, rm, rv = make_inputs(b, k, v)
    rl_f, mean_f, var_f = prodlda_recon_loss(
        theta, beta, x, rm, rv, training, 1e-5, 1e-10, True
    )
    rl_r, mean_r, var_r = prodlda_recon_loss_reference(
        theta, beta, x, rm, rv, training
    )
    np.testing.assert_allclose(rl_f, rl_r, rtol=2e-5, atol=2e-4)
    np.testing.assert_allclose(mean_f, mean_r, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(var_f, var_r, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("training", [True, False])
def test_gradient_parity(training):
    theta, beta, x, rm, rv = make_inputs(10, 6, 257)

    def loss_fused(th, be):
        rl, _, _ = prodlda_recon_loss(
            th, be, x, rm, rv, training, 1e-5, 1e-10, True
        )
        return jnp.sum(rl)

    def loss_ref(th, be):
        rl, _, _ = prodlda_recon_loss_reference(th, be, x, rm, rv, training)
        return jnp.sum(rl)

    gf_t, gf_b = jax.grad(loss_fused, argnums=(0, 1))(theta, beta)
    gr_t, gr_b = jax.grad(loss_ref, argnums=(0, 1))(theta, beta)
    np.testing.assert_allclose(gf_t, gr_t, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gf_b, gr_b, rtol=1e-4, atol=1e-4)


def test_stats_have_no_gradient_path():
    theta, beta, x, rm, rv = make_inputs(8, 4, 130)

    def mean_sum(th):
        _, mean, _ = prodlda_recon_loss(
            th, beta, x, rm, rv, True, 1e-5, 1e-10, True
        )
        return jnp.sum(mean)

    g = jax.grad(mean_sum)(theta)
    np.testing.assert_allclose(g, jnp.zeros_like(g))


def test_jit_compatible():
    theta, beta, x, rm, rv = make_inputs(8, 4, 256)

    @jax.jit
    def f(th, be, xx):
        rl, _, _ = prodlda_recon_loss(
            th, be, xx, rm, rv, True, 1e-5, 1e-10, True
        )
        return rl

    rl = f(theta, beta, x)
    rl_r, _, _ = prodlda_recon_loss_reference(theta, beta, x, rm, rv, True)
    np.testing.assert_allclose(rl, rl_r, rtol=2e-5, atol=2e-4)
