"""Federation pacing tests (ISSUE 9): cohort sampling, buffered async,
unbiased reweighting, staleness discounting, adaptive poll deadlines,
quorum denominators per policy, registry scale, and end-to-end
federations under non-sync pacing.

The scale demo (128 simulated clients over a loopback transport, marked
``slow``) is the acceptance harness: median round wall-clock at K=8 must
be <= 0.25x the all-clients sync round with FaultInjector-delayed
stragglers in the population, while the final model's NPMI stays within
tolerance of the sync run's.
"""

import itertools
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.federation import codec
from gfedntm_tpu.federation.aggregation import weighted_mean
from gfedntm_tpu.federation.client import Client
from gfedntm_tpu.federation.pacing import (
    POLL_DEADLINE_FLOOR_S,
    AsyncEngine,
    CohortEngine,
    SyncEngine,
    fallback_deadline,
    inclusion_scale,
    make_engine,
    parse_pacing,
    scale_update,
    staleness_discount,
)
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.federation.registry import (
    DROPPED,
    SUSPECT,
    ClientRecord,
    Federation,
)
from gfedntm_tpu.federation.resilience import FaultInjector
from gfedntm_tpu.federation.server import FederatedServer, build_template_model
from gfedntm_tpu.utils.observability import MetricsLogger

MODEL_KWARGS = dict(
    n_components=3, hidden_sizes=(8,), batch_size=8, num_epochs=2, seed=0,
)


# ---- spec parsing -----------------------------------------------------------

def test_parse_pacing_specs():
    assert parse_pacing(None).policy == "sync"
    assert parse_pacing("sync").spec_id == "sync"
    spec = parse_pacing("cohort:8")
    assert (spec.policy, spec.cohort_size, spec.spec_id) == (
        "cohort", 8, "cohort:8"
    )
    spec = parse_pacing("async:4", staleness_alpha=0.7, seed=3)
    assert (spec.policy, spec.buffer_size) == ("async", 4)
    assert spec.staleness_alpha == 0.7 and spec.seed == 3
    # knob forms
    assert parse_pacing("cohort", cohort_size=5).cohort_size == 5
    assert parse_pacing("async", async_buffer=2).buffer_size == 2
    # inline + matching knob is fine; conflict is loud
    assert parse_pacing("cohort:8", cohort_size=8).cohort_size == 8
    with pytest.raises(ValueError):
        parse_pacing("cohort:8", cohort_size=4)
    with pytest.raises(ValueError):
        parse_pacing("async:2", async_buffer=3)
    for bad in ("cohort", "async", "cohort:0", "async:0", "nope",
                "sync:1"):
        with pytest.raises(ValueError):
            parse_pacing(bad)
    with pytest.raises(ValueError):
        parse_pacing("sync", staleness_alpha=-1.0)


def test_server_parses_pacing_eagerly():
    with pytest.raises(ValueError):
        FederatedServer(min_clients=1, pacing_policy="cohort")  # no K
    with pytest.raises(ValueError):
        FederatedServer(min_clients=1, pacing_policy="wat")
    server = FederatedServer(
        min_clients=1, pacing_policy="cohort", cohort_size=8,
    )
    assert server.pacing.spec_id == "cohort:8"
    assert server._status()["pacing"]["policy"] == "cohort:8"


def test_make_engine_dispatch():
    server = FederatedServer(min_clients=1)
    assert type(make_engine(server, parse_pacing("sync"))) is SyncEngine
    assert isinstance(
        make_engine(server, parse_pacing("cohort:2")), CohortEngine
    )
    assert isinstance(
        make_engine(server, parse_pacing("async:2")), AsyncEngine
    )


# ---- cohort sampling --------------------------------------------------------

def _server(**kw):
    base = dict(min_clients=1, family="avitm", model_kwargs=MODEL_KWARGS)
    base.update(kw)
    server = FederatedServer(**base)
    server.template = build_template_model("avitm", 30, MODEL_KWARGS)
    return server


def _populate(server, n, ready=True):
    for cid in range(1, n + 1):
        server.federation.connect_vocab(cid, (f"w{cid}",), 10.0 + cid)
        if ready:
            server.federation.connect_ready(cid, f"localhost:{cid}")


def test_cohort_sampler_deterministic_and_seeded():
    server = _server(pacing_policy="cohort:3", pacing_seed=7)
    _populate(server, 10)
    engine = make_engine(server, server.pacing)
    active = server.federation.active_clients(0)
    roster_a = [r.client_id for r in engine.select_cohort(4, active)]
    roster_b = [r.client_id for r in engine.select_cohort(4, active)]
    assert roster_a == roster_b and len(roster_a) == 3
    # a different round (or seed) gives a different roster eventually
    others = {
        tuple(
            r.client_id for r in engine.select_cohort(i, active)
        )
        for i in range(12)
    }
    assert len(others) > 1
    # K >= eligible degenerates to everyone, inclusion probability 1
    small = active[:2]
    assert [r.client_id for r in engine.select_cohort(0, small)] == [
        r.client_id for r in small
    ]
    assert engine._inclusion_p == 1.0


def test_cohort_sampler_respects_probation_backoff():
    """Suspects inside their backoff window are not eligible — the PR 5
    registry states gate sampling exactly as they gate the sync poll."""
    server = _server(pacing_policy="cohort:4", pacing_seed=0)
    _populate(server, 6)
    server.federation.mark_suspect(
        3, "localhost:3", round_idx=0, probation_rounds=5
    )
    engine = make_engine(server, server.pacing)
    rec3 = {r.client_id: r for r in server.federation.get_clients()}[3]
    assert rec3.status == SUSPECT and rec3.next_retry_round == 1
    for round_idx in range(1):  # round 0: inside the backoff window
        active = server.federation.active_clients(round_idx)
        assert 3 not in {r.client_id for r in active}
        cohort = engine.select_cohort(round_idx, active)
        assert 3 not in {r.client_id for r in cohort}
    # once the retry round arrives, the suspect is eligible again
    active = server.federation.active_clients(1)
    assert 3 in {r.client_id for r in active}


def test_cohort_sampled_event_schema_registered():
    metrics = MetricsLogger(validate=True)
    server = _server(pacing_policy="cohort:2", metrics=metrics)
    _populate(server, 5)
    engine = make_engine(server, server.pacing)
    engine.select_cohort(0, server.federation.active_clients(0))
    events = metrics.events("cohort_sampled")
    assert events and events[0]["k"] == 2 and events[0]["eligible"] == 5
    assert len(events[0]["cohort"]) == 2


# ---- unbiased inverse-inclusion-probability reweighting ---------------------

def test_inclusion_scale_unbiased_closed_form():
    """Acceptance: enumerating every K-of-N cohort, the mean of the
    HT-corrected cohort aggregates equals the full-population weighted
    mean exactly — the closed-form expectation."""
    rng = np.random.default_rng(0)
    n, k = 4, 2
    weights = [1.0, 2.0, 3.0, 4.0]
    values = [rng.normal(size=(3, 5)).astype(np.float64) for _ in range(n)]
    g = {"x": np.zeros((3, 5))}
    w_total = sum(weights)
    p = k / n
    acc = np.zeros((3, 5))
    subsets = list(itertools.combinations(range(n), k))
    for subset in subsets:
        pairs = [(weights[i], {"x": values[i]}) for i in subset]
        est = weighted_mean(pairs)
        scale = inclusion_scale(
            sum(weights[i] for i in subset), p, w_total,
        )
        corrected = scale_update(est, g, scale)
        acc += corrected["x"]
    expectation = acc / len(subsets)
    full = weighted_mean([(w, {"x": v}) for w, v in zip(weights, values)])
    np.testing.assert_allclose(expectation, full["x"], atol=1e-12)


def test_inclusion_scale_neutral_and_capped():
    # homogeneous weights: the correction is exactly 1 (cohort mean)
    assert inclusion_scale(2.0, 0.5, 4.0) == 1.0
    # degenerate inputs are neutral, never explosive
    assert inclusion_scale(0.0, 0.5, 4.0) == 1.0
    assert inclusion_scale(2.0, 0.0, 4.0) == 1.0
    assert inclusion_scale(2.0, 0.5, 0.0) == 1.0
    # the natural bound 1/p caps a stale population-weight estimate
    assert inclusion_scale(100.0, 0.25, 1.0, max_scale=4.0) == 4.0


def test_scale_update_identity_and_affine():
    g = {"x": np.ones(4, np.float32), "n": np.arange(4)}
    avg = {"x": np.full(4, 3.0, np.float32), "n": np.arange(4)}
    assert scale_update(avg, g, 1.0) is avg  # bit-identical passthrough
    out = scale_update(avg, g, 0.5)
    np.testing.assert_allclose(out["x"], 2.0)
    assert out["x"].dtype == np.float32
    np.testing.assert_array_equal(out["n"], np.arange(4))  # non-float


def test_cohort_combine_skips_reweight_for_robust_estimators():
    """Byzantine-robust mean stages ignore sample weights by design, so
    the HT correction must not scale their estimates."""
    server = _server(
        pacing_policy="cohort:2", robust_aggregator="median",
    )
    engine = make_engine(server, server.pacing)
    engine._inclusion_p = 0.5
    engine._expected_weight = 100.0
    server._round_accepted = [(1, 5.0, 1.0), (2, 5.0, 1.0)]
    snaps = [
        (5.0, {k: np.asarray(v) for k, v in
               server._shared_template().items()})
        for _ in range(2)
    ]
    out = engine.combine(snaps, iteration=0)
    assert engine._last_scale == 1.0
    assert set(out) == set(server._shared_template())


# ---- staleness discounting --------------------------------------------------

def test_staleness_discount_closed_form():
    assert staleness_discount(0, 0.5) == 1.0
    assert staleness_discount(3, 0.0) == 1.0  # alpha 0 disables
    for s in range(5):
        np.testing.assert_allclose(
            staleness_discount(s, 0.5), 1.0 / (1.0 + s) ** 0.5
        )
    # monotone non-increasing in staleness
    vals = [staleness_discount(s, 1.0) for s in range(6)]
    assert vals == sorted(vals, reverse=True)
    assert staleness_discount(-3, 1.0) == 1.0  # clamped


def test_async_buffer_deterministic_under_arrival_order():
    """The same buffered set drains in client-id order regardless of
    arrival order, so the aggregation arithmetic (and the staleness
    discounts) are deterministic given a fixed seed/scenario."""
    server = _server(pacing_policy="async:3", staleness_alpha=0.5)
    engine = make_engine(server, server.pacing)

    def replies(order):
        out = []
        for cid in order:
            rec = ClientRecord(cid, nr_samples=4.0)
            reply = pb.StepReply(
                client_id=cid, nr_samples=4.0, base_round=cid % 3,
            )
            engine.buffer_append(rec, reply, 0.01 * cid)
            out.append((rec, reply))
        return engine.buffer_drain()

    a = replies([3, 1, 2])
    b = replies([2, 3, 1])
    assert [rec.client_id for rec, _r, _l in a] == [1, 2, 3]
    assert [rec.client_id for rec, _r, _l in b] == [1, 2, 3]
    da = engine.discounts_for(a, iteration=5)
    db = engine.discounts_for(b, iteration=5)
    assert da == db
    # staleness = iteration - base_round, discounted 1/(1+s)^alpha
    np.testing.assert_allclose(da[1], 1.0 / (1.0 + (5 - 1)) ** 0.5)
    np.testing.assert_allclose(da[3], 1.0 / (1.0 + (5 - 0)) ** 0.5)


def test_stale_discount_scales_collect_weights_and_emits_event():
    metrics = MetricsLogger(validate=True)
    server = _server(metrics=metrics, pacing_policy="async:2")
    engine = make_engine(server, server.pacing)
    tmpl = server._shared_template()
    bundle = codec.flatdict_to_bundle(tmpl)
    rec1 = ClientRecord(1, nr_samples=100.0)
    rec2 = ClientRecord(2, nr_samples=100.0)
    fresh = pb.StepReply(
        client_id=1, shared=bundle, nr_samples=8.0, base_round=4,
    )
    stale = pb.StepReply(
        client_id=2, shared=bundle, nr_samples=8.0, base_round=1,
    )
    drained = [(rec1, fresh, 0.0), (rec2, stale, 0.0)]
    discounts = engine.discounts_for(drained, iteration=4)
    out = server._collect_snapshots(
        [(rec1, fresh), (rec2, stale)], iteration=4,
        weight_scale=discounts,
    )
    weights = [w for w, _snap in out]
    np.testing.assert_allclose(weights[0], 8.0)  # s=0: undiscounted
    np.testing.assert_allclose(weights[1], 8.0 / (1.0 + 3) ** 0.5)
    events = metrics.events("update_stale_discounted")
    assert len(events) == 1 and events[0]["client"] == 2
    assert events[0]["staleness"] == 3


def test_staleness_claims_clamped_to_server_observation():
    """A byzantine client cannot widen its own norm screen by claiming
    maximal staleness: the engine clamps StepReply.base_round claims to
    the server's push-ack bookkeeping."""
    server = _server(pacing_policy="cohort:2")
    engine = make_engine(server, server.pacing)
    rec = ClientRecord(1, nr_samples=4.0)
    liar = pb.StepReply(client_id=1, base_round=0)  # "never synced"
    # the server pushed round 8 to this client and saw the ack
    with server._push_lock:
        server._push_acked[1] = 8
    stale = engine.clamped_staleness([(rec, liar)], iteration=10)
    assert stale[1] == 1  # 10 - (8 + 1), not the claimed 10
    # an honest claim below the bound passes through
    honest = pb.StepReply(client_id=1, base_round=10)
    assert engine.clamped_staleness([(rec, honest)], iteration=10)[1] == 0
    # a client the server never pushed may genuinely be on the init
    rec2 = ClientRecord(2, nr_samples=4.0)
    fresh_join = pb.StepReply(client_id=2, base_round=0)
    assert engine.clamped_staleness(
        [(rec2, fresh_join)], iteration=10
    )[2] == 10


def test_gate_screen_normalizes_staleness():
    """Cohort-aware admission: an honest-but-stale update whose raw norm
    would trip the MAD screen is admitted once norms are staleness-
    normalized — while a genuinely poisoned fresh update still rejects."""
    from gfedntm_tpu.federation.sanitize import UpdateGate

    gate = UpdateGate(mad_k=3.0, mad_rel_floor=0.1)
    g = {"x": np.zeros(16, np.float32)}
    gate.set_template(g)

    def snap(scale):
        return {"x": np.full(16, scale, np.float32)}

    # clients 1-3 fresh (norm ~1), client 4 stale by 3 rounds (norm ~4)
    candidates = [
        (1, 1.0, snap(0.25)), (2, 1.0, snap(0.26)),
        (3, 1.0, snap(0.24)), (4, 1.0, snap(1.0)),
    ]
    raw = gate.admit_round(candidates, g, 0)
    assert [r.client_id for r in raw.rejected] == [4]  # raw screen trips
    gate2 = UpdateGate(mad_k=3.0, mad_rel_floor=0.1)
    gate2.set_template(g)
    ok = gate2.admit_round(
        candidates, g, 0, staleness={4: 3},
    )
    assert not ok.rejected  # normalized: 4/(1+3) ~ the fresh peers
    # a poisoned FRESH update is still screened out under normalization
    gate3 = UpdateGate(mad_k=3.0, mad_rel_floor=0.1)
    gate3.set_template(g)
    poisoned = candidates[:3] + [(5, 1.0, snap(25.0))]
    bad = gate3.admit_round(poisoned, g, 0, staleness={4: 3})
    assert [r.client_id for r in bad.rejected] == [5]


# ---- quorum denominators (the PR 9 bugfix) ----------------------------------

def test_quorum_denominates_over_cohort_not_membership():
    """Regression (both modes): sync keeps the full-membership
    denominator; cohort denominates over the sampled cohort — against
    the membership, a K=8 sample of N=100 could never reach a 0.5
    quorum."""
    server = _server(pacing_policy="cohort:8", quorum_fraction=0.5)
    _populate(server, 100)
    cohort_engine = make_engine(server, server.pacing)
    active = server.federation.active_clients(0)
    cohort = cohort_engine.select_cohort(0, active)
    assert cohort_engine.quorum_denominator(cohort) == 8
    import math

    quorum = max(
        1, math.ceil(server.quorum_fraction
                     * cohort_engine.quorum_denominator(cohort))
    )
    assert quorum == 4  # reachable by a K=8 cohort

    sync_server = _server(quorum_fraction=0.5)
    _populate(sync_server, 100)
    sync_engine = make_engine(sync_server, sync_server.pacing)
    sync_active = sync_server.federation.active_clients(0)
    # sync: the denominator is the full unfinished membership, even when
    # handed a subset — the historical semantics, unchanged
    assert sync_engine.quorum_denominator(sync_active[:8]) == 100


# ---- adaptive poll deadline -------------------------------------------------

def test_poll_deadline_derived_from_ewmas_with_fallback():
    server = _server(local_steps=3)
    engine = make_engine(server, server.pacing)
    rec = ClientRecord(1, nr_samples=1.0)
    base = fallback_deadline(3)
    # cold start: no warm poll yet -> the historical fixed deadline
    assert engine.poll_deadline(rec) == base
    # warmed but no EWMA history -> still the fallback
    server._poll_warmed.add(1)
    assert engine.poll_deadline(rec) == base
    # fast fleet: derived deadline collapses to the floor, not 120 s
    for _ in range(3):
        server.straggler.observe_round({1: 0.02, 2: 0.03, 3: 0.025})
    assert engine.poll_deadline(rec) == POLL_DEADLINE_FLOOR_S
    # a genuinely slow fleet is never given LESS than its envelope...
    for _ in range(6):
        server.straggler.observe_round({1: 3.0, 2: 2.0, 3: 2.5})
    dl = engine.poll_deadline(rec)
    assert POLL_DEADLINE_FLOOR_S < dl < base
    assert dl >= 10.0 * 3.0  # headroom x own EWMA (EWMA converged ~3s)
    # ...and a pathological EWMA is capped at the historical constant
    for _ in range(8):
        server.straggler.observe_round({1: 50.0, 2: 40.0, 3: 45.0})
    assert engine.poll_deadline(rec) == base


# ---- push-ack round tags (delta codec under rotating cohorts) ---------------

def test_push_ack_round_tags_gate_delta_encoding():
    server = _server(wire_codec="delta")
    tmpl = server._shared_template()
    rec1, rec2 = ClientRecord(1), ClientRecord(2)
    reply = pb.StepReply(client_id=1)

    # round 0: nobody holds a broadcast -> self-contained for everyone
    aggs0 = server._encode_push(tmpl, 0, [(rec1, reply), (rec2, reply)])
    assert aggs0[1].shared.ref_round == 0
    assert aggs0[2].shared.ref_round == 0
    # both recipients acked round 0 -> round 1 deltas against it, and the
    # up-to-date recipients SHARE one encoded bundle
    with server._push_lock:
        server._push_acked.update({1: 0, 2: 0})
    aggs1 = server._encode_push(tmpl, 1, [(rec1, reply), (rec2, reply)])
    assert aggs1[1].shared.ref_round == 1  # delta vs round 0 (1 + ref)
    assert aggs1[1] is aggs1[2]
    # rotating cohort (ISSUE 11): recipient 3 last acked an OLDER round —
    # per-recipient encoding keeps the chain delta for the current
    # recipient and serves 3 an exact catch-up against ITS round, instead
    # of forcing a fleet-wide self-contained push
    rec3 = ClientRecord(3)
    with server._push_lock:
        server._push_acked.update({1: 1, 2: 1, 3: 0})
    aggs2 = server._encode_push(
        tmpl, 2, [(rec1, reply), (rec3, reply)]
    )
    assert aggs2[1].shared.ref_round == 2  # chain delta vs round 1
    assert aggs2[3].shared.ref_round == 1  # catch-up vs 3's round 0


# ---- registry + sampler scale (satellite) -----------------------------------

def _registry_workout(n: int) -> Federation:
    fed = Federation(min_clients=1)
    for cid in range(1, n + 1):
        fed.connect_vocab(cid, (f"w{cid}",), float(cid))
        fed.connect_ready(cid, f"localhost:{cid}")
    for round_idx in range(10):
        fed.active_clients(round_idx)
        fed.membership_snapshot()
        fed.alive_count()
        fed.pending_suspects(round_idx)
        for cid in range(1, n + 1, 7):  # suspect/backoff bookkeeping
            fed.mark_suspect(cid, f"localhost:{cid}", round_idx,
                             probation_rounds=50)
        for cid in range(1, n + 1, 14):
            fed.mark_recovered(cid)
    return fed


def test_registry_scale_500_time_budget_and_linear_allocation():
    """N=500 membership: snapshots, suspect/backoff bookkeeping, and
    cohort sampling complete within a CI-safe time budget and allocate
    O(N) — the peak traced allocation grows ~linearly from N=100 to
    N=500, nowhere near the 25x a quadratic structure would show."""
    import tracemalloc

    t0 = time.perf_counter()
    fed = _registry_workout(500)
    server = _server(pacing_policy="cohort:8")
    server.federation = fed
    engine = make_engine(server, server.pacing)
    for round_idx in range(50):
        active = fed.active_clients(round_idx)
        cohort = engine.select_cohort(round_idx, active)
        assert len(cohort) == 8
    elapsed = time.perf_counter() - t0
    assert elapsed < 5.0, f"registry workout took {elapsed:.1f}s at N=500"

    def peak(n):
        tracemalloc.start()
        _registry_workout(n)
        _current, peak_bytes = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        return peak_bytes

    p100, p500 = peak(100), peak(500)
    assert p500 < 15 * max(p100, 1), (
        f"allocation grew {p500 / max(p100, 1):.1f}x for 5x clients "
        f"({p100} -> {p500} bytes): not O(N)"
    )


# ---- end-to-end federations under non-sync pacing ---------------------------

def _corpora(n_clients, docs, seed=0):
    rng = np.random.default_rng(seed)
    words = [f"tok{i:02d}" for i in range(45)]
    return [
        RawCorpus(documents=[
            " ".join(rng.choice(words, size=12)) for _ in range(docs)
        ])
        for _ in range(n_clients)
    ]


def _run_federation(tmp_path, corpora, tag, *, metrics=None, injector=None,
                    poisoned_peer=None, payload=None, fault_times=64,
                    timeout=600, **server_kw):
    if injector is None and poisoned_peer is not None:
        injector = FaultInjector(seed=0, metrics=metrics)
    if poisoned_peer is not None:
        injector.script("TrainStep", kind="corrupt", payload=payload,
                        times=fault_times, peer=poisoned_peer)
    base = dict(
        min_clients=len(corpora), family="avitm",
        model_kwargs=MODEL_KWARGS, max_iters=60,
        save_dir=str(tmp_path / f"{tag}-server"), metrics=metrics,
        fault_injector=injector, checkpoint_every=0, round_backoff_s=0.05,
    )
    base.update(server_kw)
    server = FederatedServer(**base)
    addr = server.start("[::]:0")
    clients = [
        Client(client_id=c + 1, corpus=corpus, server_address=addr,
               max_features=45, save_dir=str(tmp_path / f"{tag}-c{c + 1}"),
               metrics=metrics)
        for c, corpus in enumerate(corpora)
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    try:
        assert server.wait_done(timeout=timeout), f"{tag}: did not finish"
        for t in threads:
            t.join(timeout=60)
    finally:
        server.stop()
        for c in clients:
            c.shutdown()
    return server, clients


def test_cohort_federation_e2e_with_delta_codec(tmp_path):
    """A 3-client federation under cohort:2 pacing with the delta wire
    codec: completes, every round's roster is a K<=2 sample, quorum is
    reachable (the bugfix), and the codec sessions stay consistent even
    though clients sync at different rounds (codec_ref_miss == 0)."""
    metrics = MetricsLogger(validate=True)
    server, clients = _run_federation(
        tmp_path, _corpora(3, docs=16, seed=2), "cohort", metrics=metrics,
        pacing_policy="cohort:2", pacing_seed=1, wire_codec="delta",
    )
    assert server.global_iterations > 0
    assert server.global_betas is not None
    assert np.isfinite(server.global_betas).all()
    for c in clients:
        assert c.stepper.finished and c.results is not None
    sampled = metrics.events("cohort_sampled")
    assert sampled and all(e["k"] <= 2 for e in sampled)
    # sampling actually rotates the roster
    rosters = {tuple(e["cohort"]) for e in sampled if e["eligible"] >= 3}
    assert len(rosters) > 1
    # delta/topk sessions stayed consistent across rotating cohorts
    assert metrics.registry.counter("codec_ref_miss").value == 0
    # no quorum starvation: the denominator is the cohort
    assert metrics.registry.counter("quorum_skipped_rounds").value == 0


def test_async_federation_e2e(tmp_path):
    """A 3-client federation under async:2 pacing: buffered aggregations
    happen (async_aggregated events), stale updates are discounted, and
    the run converges to a finite model with all clients finished."""
    metrics = MetricsLogger(validate=True)
    server, clients = _run_federation(
        tmp_path, _corpora(3, docs=16, seed=3), "async", metrics=metrics,
        pacing_policy="async:2", staleness_alpha=0.5,
    )
    assert server.global_iterations > 0
    assert server.global_betas is not None
    assert np.isfinite(server.global_betas).all()
    for c in clients:
        assert c.stepper.finished and c.results is not None
    aggs = metrics.events("async_aggregated")
    assert aggs and all(e["buffered"] >= 1 for e in aggs)
    status = server._status()["pacing"]
    assert status["policy"] == "async:2"


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("pacing_policy", ["cohort:3", "async:3"])
def test_poisoned_client_chaos_under_pacing(tmp_path, pacing_policy):
    """PR 5 chaos e2e under non-sync pacing: a 4-client federation where
    client 4 emits 100x-scaled updates finishes with a finite model, the
    poisoned client lands in probation with reason='poisoned', and the
    honest clients train to completion."""
    metrics = MetricsLogger(validate=True)
    server, clients = _run_federation(
        tmp_path, _corpora(4, docs=16, seed=5), f"poison-{pacing_policy}",
        metrics=metrics, poisoned_peer="client4", payload="scale:100",
        pacing_policy=pacing_policy, robust_aggregator="trimmed_mean:0.25",
        outlier_mad_k=6.0, max_iters=80,
    )
    assert server.global_betas is not None
    assert np.isfinite(server.global_betas).all()
    rejections = metrics.events("update_rejected")
    assert rejections and all(e["client"] == 4 for e in rejections)
    rec = {r.client_id: r for r in server.federation.get_clients()}[4]
    assert rec.status in (SUSPECT, DROPPED)
    assert rec.suspect_reason == "poisoned"
    for c in clients[:3]:
        assert c.stepper.finished


# ---- the 128-client scale demo (acceptance) ---------------------------------

class _LoopbackChannel:
    def close(self):
        pass


class _LoopbackStub:
    """In-process transport: invokes a FederatedClientServicer directly,
    routing TrainStep through the server's FaultInjector so scripted
    straggler delays apply exactly as they would on the wire."""

    def __init__(self, servicer, injector=None, peer=""):
        self._servicer = servicer
        self._injector = injector
        self._peer = peer

    def TrainStep(self, request, timeout=None, **_kw):
        if self._injector is not None:
            self._injector.before_call(
                "gfedntm.FederationClient", "TrainStep", request,
                peer=self._peer,
            )
        return self._servicer.TrainStep(request, None)

    def ApplyAggregate(self, request, timeout=None, **_kw):
        return self._servicer.ApplyAggregate(request, None)


class _SimServer(FederatedServer):
    """FederatedServer whose transport is loopback calls into in-process
    client servicers — full data-plane fidelity (real steppers, real
    codec bundles, real gate) without 128 gRPC servers."""

    def __init__(self, servicers, **kw):
        super().__init__(**kw)
        self._sim_servicers = servicers

    def _stub_for(self, stubs, rec):
        entry = stubs.get(rec.client_id)
        if entry is None:
            stub = _LoopbackStub(
                self._sim_servicers[rec.client_id],
                injector=self.fault_injector,
                peer=f"client{rec.client_id}",
            )
            entry = (rec.address, _LoopbackChannel(), stub)
            stubs[rec.client_id] = entry
        return entry[2]


def _topic_corpus(n_docs, vocab, topics=4, words_per_doc=18, seed=0):
    """Synthetic topical corpus: each doc draws most words from one
    latent topic's slice of the vocabulary — NPMI rewards recovering the
    slices."""
    rng = np.random.default_rng(seed)
    slice_size = len(vocab) // topics
    docs = []
    for _ in range(n_docs):
        t = int(rng.integers(topics))
        own = vocab[t * slice_size:(t + 1) * slice_size]
        words = list(rng.choice(own, size=words_per_doc - 4))
        words += list(rng.choice(vocab, size=4))  # noise
        docs.append(words)
    return docs


def _run_sim(tmp_path, tag, *, n_clients, pacing_policy, max_iters,
             straggler_delay=0.25, n_stragglers=6, **server_kw):
    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.data.vocab import Vocabulary
    from gfedntm_tpu.federated.stepper import FederatedStepper
    from gfedntm_tpu.federation.client import FederatedClientServicer

    kwargs = dict(
        n_components=4, hidden_sizes=(16,), batch_size=8, num_epochs=2,
        seed=0,
    )
    vocab_tokens = tuple(sorted(f"word{i:03d}" for i in range(60)))
    vocab = Vocabulary(vocab_tokens)
    id2token = vocab.id2token

    injector = FaultInjector(seed=0)
    for cid in range(1, n_stragglers + 1):
        # deterministic stragglers: clients 1..n_stragglers are slow on
        # EVERY poll
        injector.script(
            "TrainStep", kind="delay", delay_s=straggler_delay,
            times=10 ** 6, peer=f"client{cid}",
        )

    metrics = MetricsLogger(validate=True)
    servicers = {}
    steppers = {}
    logger = logging.getLogger(f"sim-{tag}")
    docs_per_client = 12
    for cid in range(1, n_clients + 1):
        docs = _topic_corpus(
            docs_per_client, vocab_tokens, seed=1000 + cid
        )
        X = np.zeros((docs_per_client, len(vocab_tokens)), np.float32)
        for d, words in enumerate(docs):
            for w in words:
                X[d, vocab.token2id[w]] += 1.0
        model = build_template_model("avitm", len(vocab_tokens), kwargs)
        stepper = FederatedStepper(model)
        stepper.pre_fit(BowDataset(X=X, idx2token=id2token))
        steppers[cid] = stepper
        servicers[cid] = FederatedClientServicer(
            cid, stepper, on_stop=lambda: None, logger=logger,
        )

    # Warm every client's jitted step BEFORE the timed federation: a real
    # fleet pays its trace+compile once at join time, and the sync run
    # front-loads all of it into round 0 anyway — leaving it in would
    # make the cohort medians measure jax compile scheduling, not pacing.
    # The warm call passes a throwaway rng and discards its outputs, so
    # model state (and the run's trajectory) is untouched.
    import jax
    import jax.numpy as jnp

    def warm(stepper):
        m = stepper.model
        sched = stepper._schedule
        out = stepper._step_fn(
            m.params, m.batch_stats, m.opt_state, stepper._data,
            jnp.asarray(sched.indices[0]), jnp.asarray(sched.mask[0]),
            jax.random.PRNGKey(0),
        )
        jax.block_until_ready(out[3])

    with ThreadPoolExecutor(max_workers=16) as warm_pool:
        list(warm_pool.map(warm, steppers.values()))

    server = _SimServer(
        servicers, min_clients=n_clients, family="avitm",
        model_kwargs=kwargs, max_iters=max_iters,
        save_dir=str(tmp_path / tag), metrics=metrics,
        fault_injector=injector, checkpoint_every=0,
        round_backoff_s=0.02, pacing_policy=pacing_policy,
        **server_kw,
    )
    server.global_vocab = vocab
    server.template = build_template_model(
        "avitm", len(vocab_tokens), kwargs
    )
    for cid in range(1, n_clients + 1):
        server.federation.connect_vocab(cid, (), float(docs_per_client))
        ack = server.ReadyForTraining(
            pb.JoinRequest(client_id=cid, address=f"sim:{cid}"), None
        )
        assert ack.code == 0
    assert server.wait_done(timeout=900), f"{tag}: sim did not finish"

    rounds = [
        e["seconds"] for e in metrics.events("span")
        if e.get("name") == "round"
    ]
    betas = None
    if server.last_average is not None:
        from gfedntm_tpu.eval.monitor import find_beta_key

        betas = np.asarray(
            server.last_average[find_beta_key(server.last_average)]
        )
    return server, metrics, rounds, betas


@pytest.mark.slow
def test_scale_demo_cohort_round_time_tracks_cohort(tmp_path):
    """ISSUE 9 acceptance: a 128-simulated-client federation with
    FaultInjector-delayed stragglers. Median round wall-clock under
    cohort:8 must be <= 0.25x the all-clients sync round, while the
    final model's NPMI stays within 5% (absolute-floored) of the sync
    run's on the synthetic topical corpus."""
    from gfedntm_tpu.eval.metrics import npmi_coherence
    from gfedntm_tpu.eval.monitor import topics_from_beta

    n = 128
    sync_server, _m_sync, sync_rounds, sync_betas = _run_sim(
        tmp_path, "sync", n_clients=n, pacing_policy="sync", max_iters=6,
    )
    cohort_server, m_cohort, cohort_rounds, cohort_betas = _run_sim(
        tmp_path, "cohort", n_clients=n, pacing_policy="cohort:8",
        cohort_size=None, pacing_seed=0, max_iters=120,
    )
    assert sync_rounds and cohort_rounds
    med_sync = float(np.median(sync_rounds))
    med_cohort = float(np.median(cohort_rounds))
    print(
        f"\nscale demo: sync rounds={len(sync_rounds)} med={med_sync:.3f}s"
        f" | cohort rounds={len(cohort_rounds)} med={med_cohort:.3f}s"
        f" | ratio={med_cohort / med_sync:.3f}"
    )
    assert med_cohort <= 0.25 * med_sync, (
        f"cohort:8 median round {med_cohort:.3f}s vs sync "
        f"{med_sync:.3f}s — not <= 0.25x"
    )
    # wire/compute cost is O(K): every sampled roster is K=8
    sampled = m_cohort.events("cohort_sampled")
    assert sampled
    assert max(e["k"] for e in sampled) <= 8

    # model quality: both runs converge to comparable NPMI
    assert sync_betas is not None and cohort_betas is not None
    vocab_tokens = sorted(f"word{i:03d}" for i in range(60))
    id2token = dict(enumerate(vocab_tokens))
    ref_docs = []
    for cid in range(1, n + 1):
        ref_docs.extend(
            _topic_corpus(12, tuple(vocab_tokens), seed=1000 + cid)
        )
    npmi_sync = npmi_coherence(
        topics_from_beta(sync_betas, id2token, topn=8), ref_docs, topn=8
    )
    npmi_cohort = npmi_coherence(
        topics_from_beta(cohort_betas, id2token, topn=8), ref_docs, topn=8
    )
    print(
        f"scale demo: npmi sync={npmi_sync:.4f} cohort={npmi_cohort:.4f}"
    )
    tol = max(0.05, 0.05 * abs(npmi_sync))
    assert abs(npmi_cohort - npmi_sync) <= tol, (
        f"NPMI diverged: sync {npmi_sync:.4f} vs cohort {npmi_cohort:.4f}"
    )
