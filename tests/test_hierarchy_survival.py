"""Unit coverage for the survivable-hierarchy machinery (ISSUE 17):
relay shard-journal / zero-flag autorecovery edges, the client-side
reconnect→re-home failover ladder, the root's shard-grace quorum view,
and the relaycrash/relayloss scenario personas + contracts. These are
the fast in-process complements of the real-SIGKILL e2e in
``tests/chaos/test_process_chaos.py`` and the scenario smoke cells.
"""

import os
from dataclasses import replace

import numpy as np
import pytest

from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.data.vocab import Vocabulary
from gfedntm_tpu.federation.client import Client
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.federation.registry import Federation
from gfedntm_tpu.federation.relay import RelayNode
from gfedntm_tpu.scenarios.contracts import evaluate_contracts, quorum_floor
from gfedntm_tpu.scenarios.personas import (
    RELAY_KINDS,
    fault_specs_for,
    parse_fault_persona,
)
from gfedntm_tpu.scenarios.runner import default_matrix
from gfedntm_tpu.train.checkpoint import RoundJournal
from gfedntm_tpu.utils.observability import MetricsLogger


# ---------------------------------------------------------------------------
# relay shard journal + maybe_autorecover edges
# ---------------------------------------------------------------------------

def _relay(tmp_path=None, **kw):
    kw.setdefault("relay_id", 1)
    kw.setdefault("upstream_address", "unused:0")
    kw.setdefault("min_members", 1)
    if tmp_path is not None:
        kw.setdefault("save_dir", str(tmp_path))
    return RelayNode(**kw)


def _write_journal(save_dir: str, relay: int = 1) -> RoundJournal:
    journal = RoundJournal(os.path.join(save_dir, "checkpoints"))
    journal.record(
        0, {"w": np.zeros(2, np.float32)}, [],
        vocab=["a", "b"],
        extra={
            "relay": relay, "upstream_session": "tok", "codec_id": "none",
            "setup_base_b64": "",
        },
    )
    return journal


class TestRelayJournalEdges:
    def test_fresh_start_without_journal(self, tmp_path):
        assert _relay(tmp_path).maybe_autorecover() is None

    def test_disabled_without_save_dir_or_journaling(self, tmp_path):
        assert _relay().maybe_autorecover() is None
        assert _relay(tmp_path, journal_every=0).maybe_autorecover() is None

    def test_finished_journal_starts_fresh(self, tmp_path):
        _write_journal(str(tmp_path)).mark_finished()
        assert _relay(tmp_path).maybe_autorecover() is None

    def test_foreign_shard_refused(self, tmp_path):
        """A journal written by a DIFFERENT relay id under this save_dir
        is operator error — adopting another tier's shard silently would
        double-represent its members upstream."""
        _write_journal(str(tmp_path), relay=2)
        with pytest.raises(ValueError, match="refusing to adopt"):
            _relay(tmp_path).maybe_autorecover()

    def test_journal_write_failure_degrades_loudly(self, tmp_path):
        """Satellite: ENOSPC/EIO on a shard-journal write must not kill
        training — the relay keeps serving, but it says LOUDLY (event +
        counter) that autorecovery is forfeited, and stops retrying."""
        metrics = MetricsLogger(validate=True)
        relay = _relay(tmp_path, metrics=metrics)
        relay.global_vocab = Vocabulary(("a", "b"))
        with relay._setup_lock:
            relay._setup_base = pb.GlobalSetup()

        class _BrokenJournal:
            calls = 0

            def record(self, *a, **kw):
                self.calls += 1
                raise OSError(28, "No space left on device")

        broken = _BrokenJournal()
        relay._round_journal = broken
        relay._journal_shard()
        assert relay._journal_disabled
        events = metrics.events("journal_write_failed")
        assert len(events) == 1 and "No space left" in events[0]["error"]
        assert metrics.registry.snapshot()[
            "journal_write_failures"]["value"] == 1.0
        # degraded, not flapping: further rounds skip the dead journal
        relay._journal_shard()
        assert broken.calls == 1
        assert len(metrics.events("journal_write_failed")) == 1


# ---------------------------------------------------------------------------
# client failover ladder
# ---------------------------------------------------------------------------

def _client(**kw):
    kw.setdefault("client_id", 1)
    kw.setdefault("corpus", RawCorpus(documents=["alpha beta gamma"] * 3))
    kw.setdefault("server_address", "localhost:1")
    return Client(**kw)


class _DeadChannel:
    closed = False

    def close(self):
        self.closed = True


class TestClientRehoming:
    def test_rehome_swaps_endpoint_and_resets_codec_sessions(self):
        client = _client(failover_addrs=["localhost:2", "localhost:3"])
        assert list(client.failover_addrs) == ["localhost:2", "localhost:3"]
        old = _DeadChannel()
        client._fed_channel = old
        client._federation_stub = object()

        class _Session:
            resets = 0

            def reset(self):
                self.resets += 1

        client._uplink = up = _Session()
        client._downlink = down = _Session()
        client._rehome("localhost:2")
        assert client.server_address == "localhost:2"
        assert old.closed, "the dead channel was not released"
        assert up.resets == 1 and down.resets == 1, (
            "wire-codec sessions must not survive a tier change"
        )

    def test_failover_ladder_walks_endpoints_on_exhaustion(self):
        """exhausted → pop the next endpoint and retry; any other
        outcome (finished/refused) ends the ladder — a federation that
        ANSWERED is authoritative, only a dead endpoint justifies
        re-homing."""
        client = _client(failover_addrs=["localhost:2", "localhost:3"])
        client._fed_channel = _DeadChannel()
        outcomes = iter(["exhausted", "exhausted", "ok"])
        attempts = []

        def fake_loop(idle):
            client._last_reconnect_outcome = next(outcomes)
            attempts.append(client.server_address)
            return client._last_reconnect_outcome == "ok"

        client._reconnect_loop = fake_loop
        assert client._reconnect_or_rehome(0.0)
        assert attempts == ["localhost:1", "localhost:2", "localhost:3"]
        assert client.failover_addrs == []

    def test_failover_ladder_stops_on_authoritative_answer(self):
        client = _client(failover_addrs=["localhost:2"])
        client._fed_channel = _DeadChannel()

        def fake_loop(idle):
            client._last_reconnect_outcome = "finished"
            return False

        client._reconnect_loop = fake_loop
        assert not client._reconnect_or_rehome(0.0)
        assert client.failover_addrs == ["localhost:2"], (
            "a 'finished' answer must not trigger re-homing"
        )

    def test_watchdog_window_shrinks_only_when_reconnect_available(self):
        client = _client(liveness_timeout=60.0, reconnect_window=30.0)
        client.session_token = "tok"
        client._gap_ewma = 0.1  # fast observed cadence
        # reconnect available: fast dead-server detection may shrink the
        # window below the fixed formula, floored at WATCHDOG_FLOOR_S
        assert client._watchdog_window() == pytest.approx(10.0)
        # detection would self-finalize (destructive): the observed
        # cadence may only ever WIDEN the operator's window
        client.reconnect_window = 0.0
        assert client._watchdog_window() == pytest.approx(60.0)


# ---------------------------------------------------------------------------
# root-side shard supervision: the grace view
# ---------------------------------------------------------------------------

class TestShardGrace:
    def test_grace_expired_views_long_suspects_only(self):
        fed = Federation(min_clients=2)
        fed.connect_ready(1, "a")
        fed.connect_ready(2, "b")
        fed.mark_suspect(1, "a", round_idx=5, probation_rounds=99)
        assert fed.grace_expired(6, grace_rounds=2) == []
        expired = fed.grace_expired(7, grace_rounds=2)
        assert [c.client_id for c in expired] == [1]
        # flat-fleet semantics unchanged: grace disabled → empty view
        assert fed.grace_expired(99, grace_rounds=0) == []

    def test_recovered_suspect_leaves_the_view(self):
        fed = Federation(min_clients=1)
        fed.connect_ready(1, "a")
        fed.mark_suspect(1, "a", round_idx=1, probation_rounds=99)
        assert fed.grace_expired(3, grace_rounds=2)
        assert fed.mark_recovered(1)
        assert fed.grace_expired(3, grace_rounds=2) == []


# ---------------------------------------------------------------------------
# scenario personas + contracts for the relay cells
# ---------------------------------------------------------------------------

def _matrix_cells():
    return {c.name: c for c in default_matrix()}


class TestRelayPersonas:
    def test_parse_relay_kinds(self):
        for spec, kind in (("relaycrash:3", "relaycrash"),
                           ("relayloss:2", "relayloss")):
            persona = parse_fault_persona(spec)
            assert persona.kind == kind and persona.kind in RELAY_KINDS
            assert persona.crash_round == int(spec.split(":")[1])
            # lifecycle personas are runner-driven, never injector specs
            assert fault_specs_for(persona, 4) == []

    def test_relay_kill_round_must_be_integer(self):
        with pytest.raises(ValueError, match="integer"):
            parse_fault_persona("relaycrash:1.5")

    def test_matrix_carries_the_hierarchy_cells(self):
        cells = _matrix_cells()
        crash = cells["dir01-relaycrash-sync"]
        loss = cells["dir01-relayloss-sync"]
        assert crash.fault_persona.kind == "relaycrash"
        assert loss.fault_persona.kind == "relayloss"
        assert any(s["name"] == "recovery_time" for s in crash.slo)
        # the fault axis is excluded from the baseline-twin key …
        assert replace(crash, fault="none").policy_key() == \
            crash.policy_key()
        # … and the two cells pace differently (the relayloss cell
        # stretches its runway), so each gets its own baseline twin
        assert crash.policy_key() != loss.policy_key()

    def test_shrink_pulls_the_kill_round_in(self):
        for name in ("dir01-relaycrash-sync", "dir01-relayloss-sync"):
            shrunk = _matrix_cells()[name].shrink()
            assert parse_fault_persona(shrunk.fault).crash_round <= 2

    def test_quorum_floor_is_one_for_relay_cells(self):
        cells = _matrix_cells()
        assert quorum_floor(cells["dir01-relaycrash-sync"]) == 1
        assert quorum_floor(cells["dir01-relayloss-sync"]) == 1


def _evidence(**over):
    ev = {
        "finished": True, "betas_finite": True, "rounds": 8,
        "averaged_push_clients": [2, 2, 1],
        "counters": {"codec_ref_miss": 0.0, "rpcs_deduplicated": 0.0},
        "npmi_final": 0.41,
        "slo": {
            "alerts": [{"alert": "recovery_time",
                        "objective": "recovery_time_s <= 120",
                        "state": "ok"}],
            "fired": [],
        },
    }
    ev.update(over)
    return ev


class TestRelayContracts:
    def test_relaycrash_recovery_contract(self):
        cell = _matrix_cells()["dir01-relaycrash-sync"]
        good = _evidence(
            recovery={"recovered": True, "resumed_round": 2,
                      "killed_round": 3},
            relay_recovered_events=1,
        )
        out = evaluate_contracts(cell, good)
        assert out["recovery"]["ok"], out["recovery"]["detail"]
        assert out["slo"]["ok"], out["slo"]["detail"]
        # the journal may trail by the in-flight round on each side of
        # the pre-reduction (slack 2) — but no further
        behind = _evidence(
            recovery={"recovered": True, "resumed_round": 0,
                      "killed_round": 3},
            relay_recovered_events=1,
        )
        assert not evaluate_contracts(cell, behind)["recovery"]["ok"]
        # recovery without the loud announcement is not recovery
        silent = _evidence(
            recovery={"recovered": True, "resumed_round": 3,
                      "killed_round": 3},
            relay_recovered_events=0,
        )
        assert not evaluate_contracts(cell, silent)["recovery"]["ok"]

    def test_relayloss_rehoming_contract(self):
        cell = _matrix_cells()["dir01-relayloss-sync"]
        out = evaluate_contracts(cell, _evidence(member_rehomed_events=2))
        assert out["rehoming"]["ok"], out["rehoming"]["detail"]
        assert not evaluate_contracts(
            cell, _evidence(member_rehomed_events=0)
        )["rehoming"]["ok"]
