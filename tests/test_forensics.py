"""Flight-recorder & incident-forensics tests (ISSUE 19).

Unit layer: ring bounds (entry cap, time prune, dropped accounting),
registry snapshot folding, trigger debounce + suppressed counts, bundle
atomicity/eviction, the remote-capture wire format, and the
bitwise-inert contract (`--dump_dir` unset constructs nothing and the
JSONL stream is byte-identical).

Durability layer: MetricsLogger.sync() + a killed-writer subprocess —
SIGKILL right after a capture must leave a parseable stream and a
consistent bundle.

E2E layer (seeded chaos, in-process): the PR 5 poisoned-client collapse
drives divergence rollback + quarantine; bundles land on the server AND
(via solicited remote capture) for every honest client, and the
`incident` CLI merges them into one clock-aligned postmortem naming the
trigger and the implicated client from the bundles alone. A relay kill
surfaces at the root as a client_suspect bundle while the respawned
relay's recorder starts clean.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gfedntm_tpu.cli import main as cli_main
from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.federation.client import Client
from gfedntm_tpu.federation.relay import RelayNode
from gfedntm_tpu.federation.resilience import FaultInjector
from gfedntm_tpu.federation.server import FederatedServer
from gfedntm_tpu.utils import flightrec
from gfedntm_tpu.utils.flightrec import (
    BUNDLE_PREFIX,
    BUNDLE_SCHEMA,
    FlightRecorder,
    IncidentTrigger,
    TRIGGER_EVENTS,
    build_remote_snapshot,
    bundle_filename,
    decode_bundles,
    encode_bundles,
)
from gfedntm_tpu.utils.observability import MetricsLogger, read_metrics
from gfedntm_tpu.utils.slo import SLOEngine

MODEL_KWARGS = dict(
    n_components=3, hidden_sizes=(8,), batch_size=8, num_epochs=2, seed=0,
)


def _bundles_in(dump_dir):
    """Load every bundle file in a dump dir, newest last."""
    names = sorted(
        n for n in os.listdir(dump_dir)
        if n.startswith(BUNDLE_PREFIX) and n.endswith(".json")
    )
    out = []
    for n in names:
        with open(os.path.join(dump_dir, n)) as fh:
            out.append(json.load(fh))
    return out


# ---- ring bounds -------------------------------------------------------------

class TestFlightRecorder:
    def test_entry_cap_and_dropped_accounting(self):
        rec = FlightRecorder(max_entries=8, max_seconds=3600.0)
        for i in range(20):
            rec.note("tick", i=i)
        assert len(rec) == 8
        assert rec.dropped == 12
        ring = rec.snapshot()
        # oldest-first, and the survivors are the 8 newest
        assert [r["i"] for r in ring] == list(range(12, 20))

    def test_time_prune_drops_stale_head(self):
        rec = FlightRecorder(max_entries=100, max_seconds=60.0)
        now = time.time()
        rec.observe({"event": "old", "time": now - 3600.0})
        rec.observe({"event": "older", "time": now - 120.0})
        rec.note("fresh")
        ring = rec.snapshot()
        assert [r.get("event") or r.get("kind") for r in ring] == ["fresh"]
        assert rec.dropped == 2

    def test_registry_snapshot_folded_into_ring(self):
        class Reg:
            def snapshot(self):
                return {"counter_x": {"type": "counter", "value": 3.0}}

        rec = FlightRecorder(registry=Reg(), snapshot_every_s=0.0)
        rec.note("a")
        snaps = [r for r in rec.snapshot()
                 if r.get("kind") == "registry_snapshot"]
        assert snaps and snaps[0]["metrics"]["counter_x"]["value"] == 3.0

    def test_invalid_cap_rejected(self):
        with pytest.raises(ValueError):
            FlightRecorder(max_entries=0)

    def test_note_helper_is_noop_without_recorder(self):
        # no recorder attribute at all (None target) and a logger with
        # recorder=None: both single-branch no-ops, never raising
        flightrec.note(None, "anything", x=1)
        m = MetricsLogger(validate=True)
        flightrec.note(m, "anything", x=1)


# ---- trigger seam ------------------------------------------------------------

class TestIncidentTrigger:
    def _wire(self, tmp_path, **kw):
        m = MetricsLogger(validate=True, keep_records=True, node="server")
        rec = FlightRecorder(max_entries=64)
        m.recorder = rec
        trig = IncidentTrigger(
            rec, str(tmp_path / "incidents"), metrics=m, node="server",
            **kw,
        )
        return m, rec, trig

    def test_trigger_event_dumps_atomic_bundle(self, tmp_path):
        m, rec, trig = self._wire(tmp_path)
        for i in range(10):
            m.log("checkpoint", round=i)
        m.log("divergence_rollback", round=10, reason="nonfinite_global",
              restored_round=8)
        bundles = _bundles_in(trig.dump_dir)
        assert len(bundles) == 1
        b = bundles[0]
        assert b["schema"] == BUNDLE_SCHEMA
        assert b["node"] == "server"
        assert b["reason"] == "divergence_rollback"
        assert b["trigger"]["event"] == "divergence_rollback"
        # the ring rode along, pre-trigger history included
        ring_events = [r.get("event") for r in b["ring"]]
        assert ring_events.count("checkpoint") == 10
        # process self-metrics + thread stacks are present
        assert b["process"]["pid"] == os.getpid()
        assert "Thread" in b["stacks"] or "File" in b["stacks"]
        # the capture announced itself on the stream (and did NOT
        # recurse into a second capture)
        captured = m.events("incident_captured")
        assert len(captured) == 1
        assert captured[0]["reason"] == "divergence_rollback"
        assert os.path.exists(captured[0]["path"])
        assert "incident_captured" not in TRIGGER_EVENTS

    def test_debounce_suppresses_storm_and_counts_it(self, tmp_path):
        m, rec, trig = self._wire(tmp_path, debounce_s=3600.0)
        for _ in range(5):
            m.log("alert_firing", alert="shed", metric="m",
                  value=1.0, threshold=0.5)
        assert len(_bundles_in(trig.dump_dir)) == 1
        assert trig._suppressed["slo_alert"] == 4
        # a DIFFERENT reason is not debounced by the first
        m.log("divergence_rollback", round=1, reason="loss_explosion",
              restored_round=0)
        assert len(_bundles_in(trig.dump_dir)) == 2
        # the next bundle reports what the window swallowed
        trig._last_by_reason.clear()
        m.log("alert_firing", alert="shed", metric="m",
              value=1.0, threshold=0.5)
        last = _bundles_in(trig.dump_dir)[-1]
        by_reason = {b["reason"]: b for b in _bundles_in(trig.dump_dir)}
        assert by_reason["slo_alert"]["suppressed"]["slo_alert"] >= 4
        assert last["schema"] == BUNDLE_SCHEMA

    def test_eviction_bounds_incident_dir(self, tmp_path):
        m, rec, trig = self._wire(tmp_path, debounce_s=0.0,
                                  max_bundles=3)
        for i in range(7):
            trig.capture("slo_alert", incident_id=f"i{i}")
        names = sorted(os.listdir(trig.dump_dir))
        assert len(names) == 3
        # oldest evicted first: the newest ids survive
        assert any("i6" in n for n in names)
        assert not any("i0" in n for n in names)

    def test_status_callback_failure_does_not_kill_capture(self, tmp_path):
        m = MetricsLogger(validate=True, keep_records=True, node="n")
        rec = FlightRecorder()
        m.recorder = rec
        trig = IncidentTrigger(
            rec, str(tmp_path / "inc"), metrics=m, node="n",
            status_cb=lambda: 1 / 0,
        )
        path = trig.capture("chaos")
        with open(path) as fh:
            assert json.load(fh)["status"] is None

    def test_bundle_filename_sanitized(self):
        name = bundle_filename("a/b c", "rel ay/1")
        assert name.startswith(BUNDLE_PREFIX) and name.endswith(".json")
        assert "/" not in name and " " not in name
        assert "__" in name  # the (incident, node) separator


# ---- remote-capture wire format ---------------------------------------------

class TestRemoteCapture:
    def test_encode_decode_roundtrip_and_list_contract(self):
        bundles = [{"incident_id": "x", "node": "client1", "ring": []}]
        blob = encode_bundles(bundles)
        assert decode_bundles(blob) == bundles
        import zlib
        with pytest.raises(ValueError):
            decode_bundles(zlib.compress(json.dumps({"no": 1}).encode()))

    def test_build_remote_snapshot_requires_recorder(self):
        m = MetricsLogger(validate=True, node="client1")
        assert build_remote_snapshot(m, "iid") is None
        m.recorder = FlightRecorder()
        m.recorder.note("train_step", loss=1.0)
        blob = build_remote_snapshot(m, "iid")
        (bundle,) = decode_bundles(blob)
        assert bundle["incident_id"] == "iid"
        assert bundle["reason"] == "remote_capture"
        assert bundle["node"] == "client1"
        assert bundle["ring"][0]["kind"] == "train_step"

    def test_ingest_remote_dedupes_by_filename(self, tmp_path):
        m = MetricsLogger(validate=True, keep_records=True, node="server")
        rec = FlightRecorder()
        m.recorder = rec
        trig = IncidentTrigger(rec, str(tmp_path / "inc"), metrics=m)
        blob = encode_bundles([
            {"schema": BUNDLE_SCHEMA, "incident_id": "abc",
             "node": "client2", "reason": "remote_capture",
             "time": time.time(), "ring": []},
        ])
        assert len(trig.ingest_remote(blob)) == 1
        assert trig.ingest_remote(blob) == []  # re-shipped blob is free
        assert len(m.events("flightrec_received")) == 1
        assert trig.ingest_remote(b"not a zlib blob") == []  # loss-tolerant


# ---- bitwise-inert contract --------------------------------------------------

class TestInertWithoutDumpDir:
    def test_stream_bytes_identical_with_and_without_recorder(
            self, tmp_path, monkeypatch):
        """The acceptance bar: a recorder attached to the logger must
        not change ONE byte of the JSONL stream (timestamps pinned so
        the runs are comparable)."""
        monkeypatch.setattr(time, "time", lambda: 1234567890.0)

        def run(path, with_recorder):
            m = MetricsLogger(str(path), validate=True, node="server")
            if with_recorder:
                rec = FlightRecorder(registry=None)
                m.recorder = rec
                IncidentTrigger(rec, str(tmp_path / "inc"), metrics=m,
                                node="server")
            for i in range(50):
                m.log("checkpoint", round=i)
                flightrec.note(m, "poll_dispatch", client=1, round=i)
            m.close()
            return path.read_bytes()

        off = run(tmp_path / "off.jsonl", with_recorder=False)
        on = run(tmp_path / "on.jsonl", with_recorder=True)
        assert off == on

    def test_server_without_dump_dir_constructs_nothing(self):
        m = MetricsLogger(validate=True, node="server")
        server = FederatedServer(
            min_clients=1, family="avitm", model_kwargs=MODEL_KWARGS,
            metrics=m,
        )
        assert m.recorder is None
        assert server._incident_trigger is None
        assert server.flightrec_token() == ""


# ---- crash durability --------------------------------------------------------

class TestCrashDurability:
    def test_sync_fsyncs_the_stream(self, tmp_path):
        path = tmp_path / "m.jsonl"
        m = MetricsLogger(str(path), validate=True, node="n")
        m.log("checkpoint", round=1)
        m.sync()  # must not raise, stream readable without close()
        assert [r["event"] for r in read_metrics(str(path))] == [
            "checkpoint"
        ]
        m.close()
        m.sync()  # after close: a no-op, not an error

    def test_killed_writer_leaves_parseable_stream_and_bundle(
            self, tmp_path):
        """SIGKILL the writer right after a capture: the JSONL stream
        parses cleanly (read_metrics raises on torn lines) and the
        bundle on disk is consistent with it."""
        stream = tmp_path / "victim.jsonl"
        dump = tmp_path / "incidents"
        code = f"""
import sys, time
from gfedntm_tpu.utils import flightrec
from gfedntm_tpu.utils.observability import MetricsLogger
m = MetricsLogger({str(stream)!r}, validate=False, node="victim")
rec = flightrec.FlightRecorder(max_entries=256)
m.recorder = rec
trig = flightrec.IncidentTrigger(rec, {str(dump)!r}, metrics=m,
                                 node="victim", debounce_s=0.0)
for i in range(120):
    m.log("tick", i=i)
m.log("alert_firing", alert="a", metric="m", value=2.0, threshold=1.0)
print("READY", flush=True)
time.sleep(120)
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", code], stdout=subprocess.PIPE,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env, text=True,
        )
        try:
            line = proc.stdout.readline()
            assert line.strip() == "READY"
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        records = read_metrics(str(stream))  # raises on a torn stream
        events = [r["event"] for r in records]
        assert events.count("tick") == 120
        assert "incident_captured" in events
        bundles = _bundles_in(str(dump))
        assert len(bundles) == 1
        b = bundles[0]
        assert b["reason"] == "slo_alert"
        # everything the bundle's ring references is on the synced
        # stream too — the stream-before-bundle ordering held
        assert sum(1 for r in b["ring"] if r.get("event") == "tick") > 0


# ---- SLO alert -> bundle -----------------------------------------------------

class TestSLOAlertForensics:
    def test_alert_firing_dumps_bundle_with_slo_eval_series(
            self, tmp_path):
        m = MetricsLogger(validate=True, keep_records=True, node="server")
        rec = FlightRecorder()
        m.recorder = rec
        trig = IncidentTrigger(rec, str(tmp_path / "inc"), metrics=m,
                               node="server")
        snap = {"serving_errors": {"type": "counter", "value": 0.0}}
        engine = SLOEngine(
            [dict(name="errs", metric="serving_errors", agg="value",
                  op="<=", threshold=0.0, for_s=1.0)],
            snapshot_fn=lambda: snap, metrics=m,
        )
        engine.evaluate(now=100.0)
        snap["serving_errors"]["value"] = 3.0
        engine.evaluate(now=101.0)   # pending
        engine.evaluate(now=102.5)   # firing -> capture
        bundles = _bundles_in(trig.dump_dir)
        assert len(bundles) == 1
        b = bundles[0]
        assert b["reason"] == "slo_alert"
        assert b["trigger"]["alert"] == "errs"
        # the ring holds the measured series walking into the threshold
        # — the slo_eval breadcrumbs the JSONL stream never carried
        evals = [r for r in b["ring"] if r.get("kind") == "slo_eval"]
        assert len(evals) >= 3
        assert any(r["value"] == 3.0 for r in evals)


# ---- `incident` CLI ----------------------------------------------------------

def _write_bundle(dump, incident_id, node, reason, t, trigger=None,
                  ring=(), schema=BUNDLE_SCHEMA):
    bundle = {
        "schema": schema, "incident_id": incident_id, "node": node,
        "reason": reason, "time": t, "trigger": trigger,
        "ring": list(ring), "ring_dropped": 0, "suppressed": {},
        "status": None, "process": {"pid": 1}, "stacks": "",
    }
    path = os.path.join(dump, bundle_filename(incident_id, node))
    with open(path, "w") as fh:
        json.dump(bundle, fh)
    return path


class TestIncidentCLI:
    def _seed_incident(self, dump, t=1000.0):
        os.makedirs(dump, exist_ok=True)
        server_ring = [
            {"kind": "gate_verdict", "time": t - 30 + i, "client": 3,
             "verdict": "rejected", "reason": "norm_outlier"}
            for i in range(5)
        ] + [
            {"event": "client_suspect", "time": t - 1, "client": 3,
             "failures": 1, "status": "suspect", "round": 7,
             "reason": "poisoned", "node": "server"},
        ]
        trigger = {"event": "client_quarantined", "time": t, "client": 3,
                   "round": 7, "reason": "loss_divergence",
                   "node": "server"}
        _write_bundle(dump, "abc1", "server", "quarantine", t,
                      trigger=trigger, ring=server_ring + [trigger])
        client_ring = [
            {"kind": "train_step", "time": t - 20 + i, "client": 1,
             "round": i, "loss": 1.0 - 0.01 * i}
            for i in range(6)
        ]
        _write_bundle(dump, "abc1", "client1", "remote_capture", t + 1,
                      ring=client_ring)

    def test_merge_names_trigger_and_implicated_clients(
            self, tmp_path, capsys):
        dump = str(tmp_path / "inc")
        self._seed_incident(dump)
        assert cli_main(["incident", dump]) == 0
        out = capsys.readouterr().out
        assert "incident abc1" in out
        assert "reason: quarantine" in out
        assert "client_quarantined" in out
        assert "implicated clients: 3" in out
        assert "gate:norm_outlier" in out
        assert "client_suspect" in out
        assert "train_step" in out          # the remote node's ring merged
        assert "2 bundle(s)" in out

    def test_json_report_and_limit(self, tmp_path, capsys):
        dump = str(tmp_path / "inc")
        self._seed_incident(dump)
        out_json = str(tmp_path / "report.json")
        assert cli_main(
            ["incident", dump, "--json", out_json, "--limit", "3"]
        ) == 0
        with open(out_json) as fh:
            report = json.load(fh)
        (inc,) = report["incidents"]
        assert inc["incident_id"] == "abc1"
        assert inc["reason"] == "quarantine"
        assert set(inc["nodes"]) == {"server", "client1"}
        assert "client_quarantined" in inc["implicated_clients"]["3"]
        assert any(w.startswith("gate:")
                   for w in inc["implicated_clients"]["3"])
        out = capsys.readouterr().out
        assert "last 3 of" in out

    def test_assert_no_incidents_gate(self, tmp_path, capsys):
        clean = tmp_path / "clean"
        clean.mkdir()
        assert cli_main(
            ["incident", str(clean), "--assert-no-incidents"]
        ) == 0
        assert "incident check passed" in capsys.readouterr().out
        dump = str(tmp_path / "inc")
        self._seed_incident(dump)
        assert cli_main(
            ["incident", dump, "--assert-no-incidents"]
        ) == 1
        assert "FAILED" in capsys.readouterr().err

    def test_unknown_schema_skipped_loudly(self, tmp_path, capsys):
        dump = str(tmp_path / "inc")
        os.makedirs(dump)
        _write_bundle(dump, "zzz", "server", "chaos", 5.0, schema=99)
        assert cli_main(["incident", dump]) == 0
        captured = capsys.readouterr()
        assert "unknown bundle schema" in captured.err
        assert "0 incident(s)" in captured.out

    def test_missing_path_is_loud(self, tmp_path):
        with pytest.raises(SystemExit, match="no such bundle"):
            cli_main(["incident", str(tmp_path / "nope")])

    def test_corrupt_bundle_is_loud(self, tmp_path):
        dump = tmp_path / "inc"
        dump.mkdir()
        (dump / f"{BUNDLE_PREFIX}bad__x.json").write_text("{torn")
        with pytest.raises(SystemExit, match="unreadable bundle"):
            cli_main(["incident", str(dump)])


# ---- e2e: poisoned-client collapse -> multi-node postmortem ------------------

def _corpora(sizes, seed=0):
    rng = np.random.default_rng(seed)
    words = [f"tok{i:02d}" for i in range(45)]
    return [
        RawCorpus(documents=[
            " ".join(rng.choice(words, size=12)) for _ in range(docs)
        ])
        for docs in sizes
    ]


@pytest.mark.chaos
def test_poisoned_collapse_yields_multinode_postmortem(tmp_path, capsys):
    """ISSUE 19 acceptance: a seeded poisoned-client divergence collapse
    produces atomic incident bundles for the server (local trigger) and
    every honest client (solicited remote capture), and the `incident`
    CLI merges them into one clock-aligned timeline that names the
    trigger and the implicated client — from the bundles alone, with
    >= 50 pre-trigger ring records per node."""
    dump = str(tmp_path / "incidents")
    server_metrics = MetricsLogger(validate=True, keep_records=True,
                                   node="server")
    injector = FaultInjector(seed=0, metrics=server_metrics)
    injector.script("TrainStep", kind="corrupt", payload="scale:50",
                    times=64, peer="client3", skip=55)
    server = FederatedServer(
        min_clients=3, family="avitm",
        model_kwargs=dict(MODEL_KWARGS, num_epochs=90),
        max_iters=400, save_dir=str(tmp_path / "server"),
        metrics=server_metrics, fault_injector=injector,
        checkpoint_every=4, round_backoff_s=0.02,
        sanitize=False, divergence_patience=2,
        dump_dir=dump,
    )
    # FedAvg weights are per-round sample counts: the honest clients'
    # 4-doc corpora contribute partial batches (4 samples/round) against
    # the poisoner's full 8, so its admitted weight dominates the
    # unhealthy streak; the factor is tightened because 8/16 sits under
    # the default 2x-equal-share bar.
    server.guardian.dominance_factor = 1.2
    addr = server.start("[::]:0")
    client_metrics = [
        MetricsLogger(validate=True, node=f"client{c + 1}")
        for c in range(3)
    ]
    clients = [
        Client(client_id=c + 1, corpus=corpus, server_address=addr,
               max_features=45, save_dir=str(tmp_path / f"c{c + 1}"),
               metrics=client_metrics[c],
               dump_dir=str(tmp_path / f"c{c + 1}-incidents"))
        for c, corpus in enumerate(_corpora([4, 4, 24]))
    ]
    threads = [threading.Thread(target=c.run, daemon=True)
               for c in clients]
    for t in threads:
        t.start()
    try:
        assert server.wait_done(timeout=600), "federation did not finish"
        for t in threads:
            t.join(timeout=60)
    finally:
        server.stop()
        for c in clients:
            c.shutdown()

    # the collapse really happened, through the PR 5 machinery
    rollbacks = server_metrics.events("divergence_rollback")
    assert rollbacks
    quarantined = server_metrics.events("client_quarantined")
    assert quarantined and quarantined[0]["client"] == 3
    assert server_metrics.events("flightrec_requested")
    assert server_metrics.events("flightrec_received")

    # bundles: server-local triggers AND solicited remote captures for
    # the honest clients, all in the server's incident dir
    bundles = _bundles_in(dump)
    reasons = {b["reason"] for b in bundles}
    assert "divergence_rollback" in reasons
    assert "quarantine" in reasons
    remote_nodes = {b["node"] for b in bundles
                    if b["reason"] == "remote_capture"}
    assert {"client1", "client2"} <= remote_nodes

    # the incident every node reported into: its bundles carry >= 50
    # pre-trigger ring records per node (same host, so no skew window)
    by_incident = {}
    for b in bundles:
        by_incident.setdefault(b["incident_id"], []).append(b)
    multi = {iid: grp for iid, grp in by_incident.items()
             if len({b["node"] for b in grp}) >= 3}
    assert multi, f"no multi-node incident in {sorted(by_incident)}"
    iid, group = sorted(multi.items())[0]
    reporter = next(b for b in group if b["reason"] != "remote_capture")
    for b in group:
        pre = [r for r in b["ring"]
               if float(r.get("time", 0)) <= reporter["time"] + 1.0]
        assert len(pre) >= 50, (
            f"{b['node']}: only {len(pre)} pre-trigger ring records"
        )

    # the CLI reconstructs the postmortem from the bundles alone
    trace_out = str(tmp_path / "incident_trace.json")
    json_out = str(tmp_path / "incident.json")
    assert cli_main(["incident", dump, "--json", json_out,
                     "--trace_out", trace_out]) == 0
    out = capsys.readouterr().out
    assert "reason: divergence_rollback" in out
    assert "client_quarantined" in out
    assert "implicated clients: 3" in out
    with open(json_out) as fh:
        report = json.load(fh)
    merged = {i["incident_id"]: i for i in report["incidents"]}
    assert len(merged[iid]["nodes"]) >= 3
    assert "3" in merged[iid]["implicated_clients"]
    assert all(abs(o) < 5.0
               for o in merged[iid]["clock_offsets_s"].values())
    with open(trace_out) as fh:
        trace = json.load(fh)
    assert any(e.get("ph") == "X" for e in trace["traceEvents"])

    # the CI gate flips: a dir with bundles fails, a clean one passes
    assert cli_main(["incident", dump, "--assert-no-incidents"]) == 1
    capsys.readouterr()


# ---- e2e: relay kill -> root bundle, clean respawn ---------------------------

@pytest.mark.chaos
def test_relay_kill_root_bundle_and_clean_respawn(tmp_path):
    """A relay SIGKILL-equivalent abort surfaces at the root as its
    member record entering probation (client_suspect trigger): the
    root's bundle captured the death. The respawned relay's recorder
    starts clean — its autorecovery bundle holds only post-respawn
    records."""
    root_dump = str(tmp_path / "root-incidents")
    root_metrics = MetricsLogger(validate=True, keep_records=True,
                                 node="server")
    root = FederatedServer(
        min_clients=1, family="avitm",
        model_kwargs=dict(MODEL_KWARGS, num_epochs=30),
        max_iters=500, save_dir=str(tmp_path / "root"),
        metrics=root_metrics, checkpoint_every=0, round_backoff_s=0.05,
        dump_dir=root_dump,
    )
    addr = root.start("[::]:0")
    relay_metrics = MetricsLogger(validate=True, node="relay1")
    relay_save = str(tmp_path / "relay")
    relay = RelayNode(
        relay_id=1, upstream_address=addr, min_members=2,
        metrics=relay_metrics, save_dir=relay_save,
        dump_dir=str(tmp_path / "relay-incidents"),
    )
    raddr = relay.start()
    clients = [
        Client(client_id=c + 1, corpus=corpus, server_address=raddr,
               max_features=45, save_dir=str(tmp_path / f"hc{c + 1}"))
        for c, corpus in enumerate(_corpora([24, 24], seed=3))
    ]
    threads = [threading.Thread(target=c.run, daemon=True)
               for c in clients]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 120
        while root.global_iterations < 3 and time.time() < deadline:
            time.sleep(0.05)
        assert root.global_iterations >= 3, "hierarchy never got going"
        death_time = time.time()
        relay.abort()  # SIGKILL-equivalent: no stop, no journal stamp
        while time.time() < deadline:
            if any(b["reason"] == "client_suspect"
                   for b in _bundles_in(root_dump)):
                break
            time.sleep(0.1)
        root_bundles = _bundles_in(root_dump)
        suspect = [b for b in root_bundles
                   if b["reason"] == "client_suspect"]
        assert suspect, f"no suspect bundle, got {root_bundles}"
        # the root's ring walked into the death: pre-death records exist
        assert any(float(r.get("time", 0)) < death_time
                   for r in suspect[0]["ring"])
        assert suspect[0]["trigger"]["event"] == "client_suspect"
    finally:
        root.stop()
        for c in clients:
            c.shutdown()

    # respawn: a fresh process adopting the journaled shard
    relay2_metrics = MetricsLogger(validate=True, node="relay1")
    relay2_dump = str(tmp_path / "relay2-incidents")
    relay2 = RelayNode(
        relay_id=1, upstream_address=addr, min_members=2,
        metrics=relay2_metrics, save_dir=relay_save,
        dump_dir=relay2_dump,
    )
    respawn_time = time.time()
    assert relay2.maybe_autorecover() is not None
    bundles = _bundles_in(relay2_dump)
    auto = [b for b in bundles if b["reason"] == "autorecovery"]
    assert auto, f"no autorecovery bundle, got {bundles}"
    # the respawned recorder started clean: nothing from before the
    # respawn leaked into the new ring
    assert all(
        float(r.get("time", respawn_time)) >= respawn_time - 1.0
        for r in auto[0]["ring"]
    )
    assert relay2_metrics.recorder is not None
    assert len(relay2_metrics.recorder) > 0
