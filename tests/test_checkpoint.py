"""Checkpoint/resume + scheduler + observability tests.

The key property (which the reference lacks entirely, SURVEY.md §5): a
federated run checkpointed mid-way and resumed in a fresh trainer produces
EXACTLY the same final state as an uninterrupted run (absolute-step RNG
folding makes the schedule deterministic).
"""

import numpy as np
import pytest

from gfedntm_tpu.data.datasets import BowDataset
from gfedntm_tpu.federated.trainer import FederatedTrainer
from gfedntm_tpu.models.avitm import AVITM
from gfedntm_tpu.train.schedulers import ReduceLROnPlateau, set_learning_rate
from gfedntm_tpu.utils.observability import MetricsLogger, phase_timer


def _datasets(n_clients=2, docs=12, V=32):
    rng = np.random.default_rng(3)
    idx2token = {i: f"wd{i}" for i in range(V)}
    return [
        BowDataset(
            X=rng.integers(0, 3, size=(docs, V)).astype(np.float32),
            idx2token=idx2token,
        )
        for _ in range(n_clients)
    ]


def _template(V=32):
    return AVITM(
        input_size=V, n_components=4, hidden_sizes=(8, 8), batch_size=8,
        num_epochs=4, seed=0,
    )


@pytest.mark.slow
def test_federated_resume_bitwise(tmp_path):
    datasets = _datasets()

    # Uninterrupted run.
    full = FederatedTrainer(_template(), n_clients=2, seed=1).fit(datasets)

    # Checkpointed run, interrupted after the first segment...
    ckpt = str(tmp_path / "ckpt")
    trainer_a = FederatedTrainer(_template(), n_clients=2, seed=1)
    total_steps = full.losses.shape[0]
    seg = max(1, total_steps // 2)

    class Stop(Exception):
        pass

    saved = {"n": 0}
    from gfedntm_tpu.train import checkpoint as ckpt_mod

    orig_save = ckpt_mod.CheckpointManager.save

    def save_and_stop(self, step, state, force=False):
        orig_save(self, step, state, force=force)
        saved["n"] += 1
        if not force:
            raise Stop

    ckpt_mod.CheckpointManager.save = save_and_stop
    try:
        with pytest.raises(Stop):
            trainer_a.fit(datasets, checkpoint_dir=ckpt, checkpoint_every=seg)
    finally:
        ckpt_mod.CheckpointManager.save = orig_save
    assert saved["n"] == 1

    # ...and resumed in a FRESH trainer.
    trainer_b = FederatedTrainer(_template(), n_clients=2, seed=1)
    logger = MetricsLogger()
    resumed = trainer_b.fit(
        datasets, checkpoint_dir=ckpt, checkpoint_every=seg, resume=True,
        metrics=logger,
    )

    assert logger.events("resume")[0]["step"] == seg
    np.testing.assert_allclose(resumed.losses, full.losses, rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(resumed.client_params["beta"]),
        np.asarray(full.client_params["beta"]),
    )


def test_reduce_on_plateau_semantics():
    sched = ReduceLROnPlateau(1.0, factor=0.5, patience=2, threshold=0.0)
    assert sched.step(10.0) == 1.0  # first metric becomes best
    assert sched.step(9.0) == 1.0  # improvement
    assert sched.step(9.5) == 1.0  # bad 1
    assert sched.step(9.5) == 1.0  # bad 2
    assert sched.step(9.5) == 0.5  # bad 3 > patience -> reduce
    assert sched.step(9.5) == 0.5  # counter reset


@pytest.mark.slow
def test_injected_lr_is_mutable_and_used():
    model = AVITM(
        input_size=16, n_components=3, hidden_sizes=(8,), batch_size=8,
        num_epochs=2, reduce_on_plateau=True, seed=0,
    )
    assert hasattr(model.opt_state, "hyperparams")
    rng = np.random.default_rng(0)
    data = BowDataset(
        X=rng.integers(0, 3, size=(16, 16)).astype(np.float32),
        idx2token={i: str(i) for i in range(16)},
    )
    model.fit(data, n_samples=2)
    # forcing lr to 0 must freeze params
    set_learning_rate(model.opt_state, 0.0)
    before = np.asarray(model.params["beta"]).copy()
    model.num_epochs = 1
    model.fit(data, n_samples=2)
    np.testing.assert_array_equal(before, np.asarray(model.params["beta"]))


def test_metrics_logger_jsonl(tmp_path):
    import json

    path = str(tmp_path / "metrics.jsonl")
    with MetricsLogger(path) as logger:
        logger.log("epoch", epoch=0, loss=1.5)
        with phase_timer(logger, "train"):
            pass
    lines = [json.loads(ln) for ln in open(path)]
    assert lines[0]["event"] == "epoch" and lines[0]["loss"] == 1.5
    assert lines[1]["event"] == "phase" and lines[1]["seconds"] >= 0


def test_local_steps_resume_bitwise(tmp_path):
    """The E>1 exchange schedule is indexed by ABSOLUTE step, so a
    checkpoint-resumed run must reproduce the uninterrupted one exactly
    even when the resume point falls between exchanges."""
    import numpy as np

    from gfedntm_tpu.federated.trainer import FederatedTrainer

    datasets = _datasets()
    # 2 clients x 12 docs, B=8 -> 2 steps/epoch; 4 epochs = 8 steps.
    # E=3: exchanges END of absolute steps 2, 5, and 7 (forced final).
    full = FederatedTrainer(
        _template(), n_clients=2, seed=1, local_steps=3
    ).fit(datasets)

    ckpt = str(tmp_path / "ck")
    tr_a = FederatedTrainer(
        _template(), n_clients=2, seed=1, local_steps=3
    )
    # Stop after 2 segments of 3 steps (absolute step 6 — mid-period).
    stop = {"n": 0}

    class _Stop(Exception):
        pass

    def cb(step, params, batch_stats):
        stop["n"] += 1
        if stop["n"] == 2:
            raise _Stop()

    try:
        tr_a.fit(datasets, checkpoint_dir=ckpt, checkpoint_every=3,
                 segment_callback=cb)
    except _Stop:
        pass

    tr_b = FederatedTrainer(
        _template(), n_clients=2, seed=1, local_steps=3
    )
    resumed = tr_b.fit(datasets, checkpoint_dir=ckpt, checkpoint_every=3,
                       resume=True)
    np.testing.assert_array_equal(resumed.losses, full.losses)
    np.testing.assert_array_equal(
        np.asarray(resumed.client_params["beta"]),
        np.asarray(full.client_params["beta"]),
    )
