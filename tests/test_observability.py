"""Telemetry subsystem tests (tier-1): span nesting/ids, histogram bucket
edges + quantile estimation, thread-safe concurrent logging, schema lint,
`summarize` on a golden metrics.jsonl, and a 2-client in-process federated
smoke run asserting the full event set (round spans, RPC latency, codec
bytes, step-time histograms) renders through the CLI report."""

import json
import threading

import numpy as np
import pytest

from gfedntm_tpu.utils.observability import (
    DEFAULT_BYTE_BUCKETS,
    Histogram,
    MetricRegistry,
    MetricsLogger,
    format_report,
    quantile_from_snapshot,
    read_metrics,
    span,
    summarize_metrics,
    timed_jit,
    validate_record,
)


# ---- spans -----------------------------------------------------------------

class TestSpans:
    def test_nesting_ids_and_order(self):
        log = MetricsLogger(validate=True)
        with span(log, "round", round=3) as outer:
            with span(log, "poll") as inner:
                pass
        assert outer.parent_id is None
        assert inner.parent_id == outer.span_id
        assert inner.span_id != outer.span_id
        # children exit (and log) before their parent
        events = log.events("span")
        assert [e["name"] for e in events] == ["poll", "round"]
        assert events[1]["round"] == 3
        assert all(e["seconds"] >= 0 for e in events)

    def test_sibling_spans_share_parent(self):
        log = MetricsLogger()
        with span(log, "round") as r:
            with span(log, "poll") as a:
                pass
            with span(log, "push") as b:
                pass
        assert a.parent_id == r.span_id and b.parent_id == r.span_id

    def test_explicit_parent_across_threads(self):
        """Pool threads don't inherit contextvars; parent= carries the
        hierarchy across the boundary (the server's poll/push workers)."""
        log = MetricsLogger()
        seen = {}

        with span(log, "round") as r:
            def worker():
                with span(log, "poll", parent=r) as p:
                    seen["parent"] = p.parent_id

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["parent"] == r.span_id

    def test_annotate_and_failure_flag(self):
        log = MetricsLogger()
        with pytest.raises(RuntimeError):
            with span(log, "round") as r:
                r.annotate(clients=2)
                raise RuntimeError("boom")
        (ev,) = log.events("span")
        assert ev["clients"] == 2 and ev["ok"] is False

    def test_null_span_without_logger(self):
        s = span(None, "anything")
        with s as inner:
            inner.annotate(a=1)
        assert inner.span_id is None and inner.parent_id is None


# ---- metric registry --------------------------------------------------------

class TestHistogram:
    def test_bucket_edges_are_upper_inclusive(self):
        h = Histogram("t", buckets=(1.0, 2.0, 4.0))
        for v in (1.0, 1.5, 2.0, 4.0, 4.0001, 100.0):
            h.observe(v)
        snap = h.snapshot()
        # v <= edge lands in that bucket; beyond the last edge overflows
        assert snap["counts"] == [1, 2, 1, 2]
        assert snap["count"] == 6
        assert snap["min"] == 1.0 and snap["max"] == 100.0

    def test_quantiles_from_uniform_observations(self):
        h = Histogram("t")  # default time buckets
        for ms in range(1, 101):  # 1..100 ms uniform
            h.observe(ms / 1000.0)
        p50, p95 = h.quantile(0.5), h.quantile(0.95)
        assert 0.025 <= p50 <= 0.075
        assert 0.080 <= p95 <= 0.100
        assert h.quantile(0.99) <= 0.100  # clamped to observed max

    def test_quantile_from_serialized_snapshot(self):
        h = Histogram("bytes", buckets=DEFAULT_BYTE_BUCKETS)
        for _ in range(10):
            h.observe(2048)
        snap = json.loads(json.dumps(h.snapshot()))  # JSONL round-trip
        assert quantile_from_snapshot(snap, 0.5) == pytest.approx(2048)
        assert quantile_from_snapshot({"count": 0}, 0.5) is None

    def test_registry_get_or_create_and_type_guard(self):
        reg = MetricRegistry()
        c = reg.counter("n")
        c.inc()
        c.inc(2.5)
        assert reg.counter("n") is c and c.value == 3.5
        reg.gauge("g").set(7)
        assert reg.gauge("g").value == 7.0
        with pytest.raises(TypeError):
            reg.histogram("n")
        snap = reg.snapshot()
        assert snap["n"] == {"type": "counter", "value": 3.5}
        assert snap["g"] == {"type": "gauge", "value": 7.0}


# ---- logger: thread safety + schema ----------------------------------------

class TestLogger:
    def test_concurrent_logging_keeps_stream_intact(self, tmp_path):
        """Interleaved writes from worker threads (the federation server's
        poll/push pool) must produce one valid JSON object per line."""
        path = str(tmp_path / "metrics.jsonl")
        n_threads, n_each = 8, 200
        with MetricsLogger(path, keep_records=True) as log:
            def work(tid):
                for i in range(n_each):
                    log.log("epoch", epoch=i, thread=tid)

            threads = [
                threading.Thread(target=work, args=(t,))
                for t in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(log.records) == n_threads * n_each
        records = read_metrics(path)  # raises on any corrupt line
        assert len(records) == n_threads * n_each
        for r in records:
            validate_record(r)

    def test_path_backed_logger_skips_retention_by_default(self, tmp_path):
        """A long path-backed run must not accumulate every event in memory;
        in-process consumers opt in via keep_records=True (or path=None)."""
        path = str(tmp_path / "m.jsonl")
        with MetricsLogger(path) as log:
            log.log("epoch", epoch=0)
            assert log.records == []
            with pytest.raises(RuntimeError, match="keep_records"):
                log.events("epoch")
        assert len(read_metrics(path)) == 1
        mem = MetricsLogger()
        mem.log("epoch", epoch=1)
        assert mem.events("epoch")[0]["epoch"] == 1

    def test_validate_record_schema_lint(self):
        validate_record({"event": "phase", "time": 1.0, "phase": "x",
                         "seconds": 0.5})
        with pytest.raises(ValueError, match="missing required"):
            validate_record({"event": "phase", "time": 1.0, "phase": "x"})
        with pytest.raises(ValueError, match="unknown event"):
            validate_record({"event": "not_a_real_event", "time": 1.0})
        # envelope is still checked under non-strict
        validate_record({"event": "future_event", "time": 1.0}, strict=False)
        with pytest.raises(ValueError):
            validate_record({"event": "", "time": 1.0}, strict=False)
        with pytest.raises(ValueError):
            validate_record({"event": "phase", "phase": "x", "seconds": 1.0})

    def test_validating_logger_rejects_drift(self):
        log = MetricsLogger(validate=True)
        with pytest.raises(ValueError):
            log.log("unregistered_event", x=1)

    def test_timed_jit_compile_capture(self):
        log = MetricsLogger()
        calls = []
        fn = timed_jit(lambda x: calls.append(x) or x * 2, log, "train_step")
        assert fn(3) == 6 and fn(4) == 8 and fn(5) == 10
        (compile_ev,) = log.events("jit_compile")
        assert compile_ev["what"] == "train_step"
        hist = log.registry.histogram("jit_dispatch_s/train_step")
        assert hist.count == 2  # first call went to jit_compile instead

    def test_timed_jit_noop_without_logger(self):
        fn = lambda x: x  # noqa: E731
        assert timed_jit(fn, None, "x") is fn


# ---- summarize on a golden stream ------------------------------------------

def _golden_records():
    """A deterministic miniature run: 2 rounds of a 2-client federation
    plus a registry snapshot — the documented event set."""
    h_edges = [0.001, 0.01, 0.1, 1.0]
    rec = []
    t = 1_700_000_000.0

    def ev(event, **fields):
        nonlocal t
        t += 0.25
        rec.append({"event": event, "time": t, **fields})

    ev("phase", phase="consensus", seconds=0.5)
    ev("jit_compile", what="train_step", seconds=2.0)
    sid = 0
    for rnd in range(2):
        base = sid
        ev("span", name="poll", span_id=base + 2, parent_id=base + 1,
           seconds=0.08, ok=True, clients=2)
        ev("span", name="average", span_id=base + 3, parent_id=base + 1,
           seconds=0.01, ok=True)
        ev("span", name="push", span_id=base + 4, parent_id=base + 1,
           seconds=0.04, ok=True, clients=2)
        ev("span", name="round", span_id=base + 1, parent_id=None,
           seconds=0.2, ok=True, round=rnd, clients=2,
           bytes_pulled=4096, bytes_pushed=2048,
           slowest_client=2, slowest_s=0.07)
        sid += 4
    ev("rpc", service="gfedntm.FederationClient", method="TrainStep",
       seconds=0.5, ok=False, code="DEADLINE_EXCEEDED", peer="client1")
    ev("metrics_snapshot", metrics={
        "stepper_step_s": {
            "type": "histogram", "count": 100, "sum": 5.0,
            "min": 0.02, "max": 0.4, "edges": h_edges,
            "counts": [0, 0, 90, 10],
        },
        "rpc_s/FederationClient.TrainStep": {
            "type": "histogram", "count": 4, "sum": 0.2,
            "min": 0.03, "max": 0.09, "edges": h_edges,
            "counts": [0, 0, 4, 0],
        },
        "rpc_deadline_expired": {"type": "counter", "value": 1},
        "rpc_errors": {"type": "counter", "value": 1},
        "codec_encoded_bytes": {"type": "counter", "value": 8192},
        "codec_decoded_bytes": {"type": "counter", "value": 4096},
        "codec_encode_calls": {"type": "counter", "value": 4},
        "codec_decode_calls": {"type": "counter", "value": 4},
    })
    ev("summary", n_clients=2, final_mean_loss=12.5)
    return rec


class TestSummarize:
    def test_golden_records_validate(self):
        for r in _golden_records():
            validate_record(r)

    def test_summary_aggregates(self):
        s = summarize_metrics(_golden_records())
        assert s["rounds"]["count"] == 2
        assert s["rounds"]["bytes_pulled"] == 8192
        assert s["rounds"]["bytes_pushed"] == 4096
        assert s["slowest_clients"][2]["rounds_slowest"] == 2
        assert s["phases"]["consensus"]["total_s"] == 0.5
        assert s["spans"]["poll"]["count"] == 2
        st = s["step_time"]["stepper_step_s"]
        assert st["count"] == 100
        assert 0.01 < st["p50_s"] <= 0.1
        assert st["p99_s"] <= 0.4
        assert s["rpc"]["FederationClient.TrainStep"]["count"] == 4
        assert s["rpc_errors"] == 1
        assert s["counters"]["rpc_deadline_expired"] == 1
        assert s["compile"] == [{"what": "train_step", "seconds": 2.0}]
        assert s["summary"]["final_mean_loss"] == 12.5

    def test_cli_summarize_renders_report(self, tmp_path, capsys):
        from gfedntm_tpu.cli import main

        path = tmp_path / "metrics.jsonl"
        with path.open("w") as fh:
            for r in _golden_records():
                fh.write(json.dumps(r) + "\n")
        json_out = tmp_path / "summary.json"
        rc = main(["summarize", str(path), "--json", str(json_out)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "phase breakdown" in out
        assert "p95" in out and "p99" in out
        assert "stepper_step_s" in out
        assert "federation rounds: 2" in out
        assert "slowest client: 2" in out
        assert "deadline expiries" in out
        assert "encoded" in out
        loaded = json.loads(json_out.read_text())
        assert loaded["rounds"]["count"] == 2

    def test_cli_summarize_missing_file(self):
        from gfedntm_tpu.cli import main

        with pytest.raises(SystemExit, match="no such metrics file"):
            main(["summarize", "/nonexistent/metrics.jsonl"])

    def test_read_metrics_rejects_corrupt_line(self, tmp_path):
        path = tmp_path / "m.jsonl"
        path.write_text('{"event": "phase"}\n{not json\n')
        with pytest.raises(ValueError, match="bad JSONL"):
            read_metrics(str(path))


# ---- end-to-end: instrumented 2-client federated round ----------------------

def _tiny_corpora(n_clients=2, docs=10, seed=0):
    rng = np.random.default_rng(seed)
    words = [f"tok{i:02d}" for i in range(40)]
    from gfedntm_tpu.data.loaders import RawCorpus

    return [
        RawCorpus(documents=[
            " ".join(rng.choice(words, size=12)) for _ in range(docs)
        ])
        for _ in range(n_clients)
    ]


class TestFederatedSmokeTelemetry:
    def test_two_client_round_emits_expected_event_set(self, tmp_path):
        """An in-process 2-client federation writes one metrics.jsonl with
        round-scoped spans, RPC latency + codec byte registry state, and
        step-time histogram snapshots — and `summarize` renders it."""
        from gfedntm_tpu.cli import main as cli_main
        from gfedntm_tpu.federation.client import Client
        from gfedntm_tpu.federation.server import FederatedServer

        path = str(tmp_path / "metrics.jsonl")
        # ONE logger shared by the server and both in-process clients:
        # exactly the concurrent multi-writer regime it must survive.
        metrics = MetricsLogger(path, validate=True)
        model_kwargs = dict(
            n_components=3, hidden_sizes=(8,), batch_size=8, num_epochs=1,
            seed=0,
        )
        server = FederatedServer(
            min_clients=2, family="avitm", model_kwargs=model_kwargs,
            max_iters=50, save_dir=str(tmp_path / "server"),
            metrics=metrics,
        )
        addr = server.start("[::]:0")
        clients = [
            Client(
                client_id=c + 1, corpus=corpus, server_address=addr,
                max_features=40, save_dir=str(tmp_path / f"client{c + 1}"),
                metrics=metrics,
            )
            for c, corpus in enumerate(_tiny_corpora())
        ]
        threads = [
            threading.Thread(target=c.run, daemon=True) for c in clients
        ]
        for t in threads:
            t.start()
        assert server.wait_done(timeout=300.0)
        for t in threads:
            t.join(timeout=60.0)
        for c in clients:
            c.shutdown()
        server.stop()
        metrics.close()

        records = read_metrics(path)
        for r in records:
            validate_record(r)
        by_event = {}
        for r in records:
            by_event.setdefault(r["event"], []).append(r)

        # round-scoped span hierarchy
        spans = {s["name"]: s for s in by_event["span"]}
        for name in ("round", "poll", "average", "push"):
            assert name in spans, f"missing {name} span"
        rounds = [s for s in by_event["span"] if s["name"] == "round"]
        polls = [s for s in by_event["span"] if s["name"] == "poll"]
        round_ids = {s["span_id"] for s in rounds}
        assert all(p["parent_id"] in round_ids for p in polls)
        assert any("bytes_pulled" in s and s["bytes_pulled"] > 0
                   for s in rounds)
        assert any(s.get("slowest_client") in (1, 2) for s in rounds)

        # client-side join/finalize spans + compile capture
        assert "get_setup" in spans and "finalize" in spans
        compiles = {c["what"] for c in by_event["jit_compile"]}
        assert "train_step" in compiles

        # cumulative registry state: RPC latency, codec bytes, step times
        merged = {}
        for snap_ev in by_event["metrics_snapshot"]:
            merged.update(snap_ev["metrics"])
        assert merged["rpc_s/FederationClient.TrainStep"]["count"] > 0
        assert merged["codec_encoded_bytes"]["value"] > 0
        assert merged["codec_decoded_bytes"]["value"] > 0
        assert merged["stepper_step_s"]["count"] > 0
        assert merged["client_poll_s"]["count"] > 0
        assert "round_slowest_client_id" in merged
        staleness = [k for k in merged if k.startswith("client_staleness_mb/")]
        assert staleness

        # and the CLI report renders from it
        import contextlib
        import io

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main(["summarize", path])
        assert rc == 0
        out = buf.getvalue()
        assert "federation rounds:" in out
        assert "rpc latency" in out
        assert "stepper_step_s" in out
        assert "bytes moved" in out
        # non-step/non-rpc histograms (codec, poll latency) render too
        assert "other distributions" in out
        assert "client_poll_s" in out and "codec_encode_s" in out

    def test_uninstrumented_federation_unchanged(self):
        """metrics=None everywhere -> the no-op path: stubs/codec/stepper
        hooks must add nothing and require nothing."""
        from gfedntm_tpu.federation import codec

        bundle = codec.flatdict_to_bundle({"a": np.ones(3, np.float32)})
        out = codec.bundle_to_flatdict(bundle)
        np.testing.assert_array_equal(out["a"], np.ones(3, np.float32))


class TestTrainerTelemetry:
    def test_spmd_fit_emits_step_histogram_and_compile(self):
        """FederatedTrainer.fit: first fit captures the program compile;
        a second fit (compiled program reused) feeds trainer_step_s; both
        snapshot into the stream."""
        from gfedntm_tpu.data.datasets import BowDataset
        from gfedntm_tpu.federated.trainer import FederatedTrainer
        from gfedntm_tpu.models.avitm import AVITM

        rng = np.random.default_rng(0)
        datasets = [
            BowDataset(
                X=rng.integers(0, 3, size=(12, 16)).astype(np.float32),
                idx2token={i: str(i) for i in range(16)},
            )
            for _ in range(2)
        ]
        import jax

        if not hasattr(jax, "shard_map"):
            # Same environment gap that fails the seed's test_federated.py
            # suite on old CPU-only jax; the SPMD program can't build at all.
            pytest.skip("jax.shard_map unavailable in this environment")
        template = AVITM(
            input_size=16, n_components=3, hidden_sizes=(8,), batch_size=8,
            num_epochs=2, seed=0,
        )
        trainer = FederatedTrainer(template, n_clients=2, seed=0)
        log = MetricsLogger(validate=True)
        trainer.fit(datasets, metrics=log)
        compiles = log.events("jit_compile")
        assert any(c["what"] == "federated_program" for c in compiles)
        assert log.events("metrics_snapshot")
        # steady-state fit at the same segment length: no new compile event,
        # per-segment average step time lands in the histogram
        trainer.fit(datasets, metrics=log)
        assert len(log.events("jit_compile")) == len(compiles)
        snap = log.events("metrics_snapshot")[-1]["metrics"]
        assert snap["trainer_step_s"]["count"] >= 1
