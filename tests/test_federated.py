"""Federated SPMD core: exchange math, share masks, multi-device meshes.

Runs on the 8-virtual-CPU-device mesh from conftest (the reference's
docker-compose multi-node setup, SURVEY.md §4.4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gfedntm_tpu.config import SHARE_MINIMAL
from gfedntm_tpu.data import BowDataset, generate_synthetic_corpus
from gfedntm_tpu.federated import FederatedTrainer
from gfedntm_tpu.models import AVITM
from gfedntm_tpu.train.steps import _batch_loss

V, K = 60, 4


def _datasets(n_nodes=2, n_docs=50, seed=0):
    corpus = generate_synthetic_corpus(
        vocab_size=V, n_topics=K, n_docs=n_docs, nwords=(10, 20),
        n_nodes=n_nodes, frozen_topics=K, seed=seed,
    )
    idx2token = {i: f"wd{i}" for i in range(V)}
    return [BowDataset(X=n.bow, idx2token=idx2token) for n in corpus.nodes], corpus


def _template(num_epochs=2, dropout=0.2, batch_size=16, seed=0):
    return AVITM(
        input_size=V, n_components=K, hidden_sizes=(12, 12),
        num_epochs=num_epochs, batch_size=batch_size, dropout=dropout, seed=seed,
    )


@pytest.mark.slow
def test_share_all_makes_params_identical_across_clients():
    dsets, _ = _datasets(3)
    ft = FederatedTrainer(_template(), n_clients=3)
    res = ft.fit(dsets)
    for leaf in jax.tree.leaves(res.client_params):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            for c in range(1, 3):
                np.testing.assert_allclose(arr[0], arr[c], rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_segment_callback_snapshots_each_segment():
    """fit(segment_callback=...) fires once per completed segment with the
    absolute step count and host-synced state, and does not change the
    result (time_to_quality.py relies on both properties)."""
    dsets, _ = _datasets(2, n_docs=32)
    t = _template(num_epochs=4, batch_size=16)
    spe = 2  # ceil(32/16)
    seen = []

    def cb(step, params, batch_stats):
        seen.append((step, np.asarray(params["beta"][0]).copy()))

    res = FederatedTrainer(t, n_clients=2, seed=5).fit(
        dsets, checkpoint_every=spe, segment_callback=cb
    )
    total = int(res.losses.shape[0])
    assert [s for s, _ in seen] == list(range(spe, total + 1, spe))
    # callback state matches the final result at the last segment
    np.testing.assert_allclose(
        seen[-1][1], np.asarray(res.client_params["beta"][0]),
        rtol=1e-6, atol=1e-7,
    )
    # segmentation + callback must not perturb the run
    ref = FederatedTrainer(t, n_clients=2, seed=5).fit(dsets)
    np.testing.assert_allclose(
        np.asarray(ref.client_params["beta"][0]), seen[-1][1],
        rtol=1e-5, atol=1e-6,
    )


@pytest.mark.slow
def test_share_minimal_keeps_encoders_local():
    dsets, _ = _datasets(2)
    ft = FederatedTrainer(_template(), n_clients=2, grads_to_share=SHARE_MINIMAL)
    res = ft.fit(dsets)
    beta = np.asarray(res.client_params["beta"])
    np.testing.assert_allclose(beta[0], beta[1], rtol=1e-5, atol=1e-6)
    enc = np.asarray(res.client_params["inf_net"]["input_layer"]["kernel"])
    assert not np.allclose(enc[0], enc[1]), "encoders must stay client-local"


@pytest.mark.slow
def test_federated_run_is_deterministic():
    dsets, _ = _datasets(2)
    r1 = FederatedTrainer(_template(), n_clients=2, seed=5).fit(dsets)
    r2 = FederatedTrainer(_template(), n_clients=2, seed=5).fit(dsets)
    np.testing.assert_allclose(r1.losses, r2.losses, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(r1.client_params["beta"]), np.asarray(r2.client_params["beta"]),
        rtol=1e-6,
    )


def test_losses_decrease_over_epochs():
    dsets, _ = _datasets(2, n_docs=80)
    ft = FederatedTrainer(_template(num_epochs=6), n_clients=2)
    res = ft.fit(dsets)
    for per_client in res.epoch_losses:
        assert per_client[-1] < per_client[0]


@pytest.mark.slow
def test_one_step_exchange_matches_manual_average():
    """The psum-weighted exchange must equal a hand-computed weighted average
    of independently-stepped clients (server.py:476-487 semantics)."""
    dsets, _ = _datasets(2, n_docs=20)
    # num_epochs=1 & batch >= n_docs -> exactly one global step
    t = _template(num_epochs=1, dropout=0.0, batch_size=32)
    ft = FederatedTrainer(t, n_clients=2, seed=3)
    res = ft.fit(dsets)

    # Manually replicate each client's single step with the trainer's rng
    # folding scheme, then average with weights n_c.
    rng = jax.random.PRNGKey(3 + 17)
    w = np.array([len(d) for d in dsets], np.float32)
    from gfedntm_tpu.data.datasets import make_run_schedule

    stepped = []
    for c, d in enumerate(dsets):
        sched = make_run_schedule(len(d), 32, 1, seed=3 * 1000 + c)
        step_rng = jax.random.fold_in(jax.random.fold_in(rng, 0), c)
        rngs = {
            "dropout": jax.random.fold_in(step_rng, 0),
            "reparam": jax.random.fold_in(step_rng, 1),
        }
        x = jnp.asarray(d.X)[jnp.asarray(sched.indices[0])]
        mask = jnp.asarray(sched.mask[0])

        def loss_fn(p):
            return _batch_loss(
                t.module, "avitm", 1.0, p, t.batch_stats, {"x_bow": x}, mask,
                rngs, train=True,
            )

        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(t.params)
        updates, _ = t.tx.update(grads, t.tx.init(t.params), t.params)
        import optax

        stepped.append(optax.apply_updates(t.params, updates))

    expected_beta = (
        w[0] * np.asarray(stepped[0]["beta"]) + w[1] * np.asarray(stepped[1]["beta"])
    ) / w.sum()
    np.testing.assert_allclose(
        np.asarray(res.client_params["beta"][0]), expected_beta, rtol=1e-4, atol=1e-6
    )


def test_make_global_model_and_device_resident_global_params():
    """`global_params` stays device-resident (round 4: host
    materialization cost ~0.6 s/fit in tunnel round-trips) but must
    still (a) convert to numpy lazily, (b) equal client 0's post-psum
    shared leaves, and (c) feed `make_global_model` -> `get_topics`."""
    dsets, _ = _datasets(2, n_docs=32)
    ft = FederatedTrainer(_template(num_epochs=1), n_clients=2)
    res = ft.fit(dsets)

    beta_global = np.asarray(res.global_params["beta"])  # lazy host copy
    np.testing.assert_array_equal(
        beta_global, np.asarray(res.client_params["beta"][0])
    )

    gm = ft.make_global_model(res)
    gm.train_data = dsets[0]
    topics = gm.get_topics(5)
    assert len(topics) == K and all(len(t) == 5 for t in topics)


def test_unequal_client_sizes_cycle_epochs():
    """Clients with different dataset sizes run the same number of global
    steps; the smaller client cycles extra epochs (federated_avitm.py:114-138
    iterator-reset semantics)."""
    c1, _ = _datasets(1, n_docs=64, seed=1)
    c2, _ = _datasets(1, n_docs=16, seed=2)
    dsets = [c1[0], c2[0]]
    ft = FederatedTrainer(_template(num_epochs=2, batch_size=16), n_clients=2)
    res = ft.fit(dsets)
    assert res.losses.shape[0] == 8  # max steps/epoch (4) * 2 epochs
    assert len(res.epoch_losses[0]) == 2
    assert len(res.epoch_losses[1]) == 8  # small client cycled 8 epochs


@pytest.mark.slow
def test_more_clients_than_devices_pads_and_runs():
    dsets, _ = _datasets(3, n_docs=20)
    # force a 2-device mesh with 3 clients -> c_pad = 4
    devices = jax.devices()[:2]
    ft = FederatedTrainer(
        _template(num_epochs=1, batch_size=16), n_clients=3, devices=devices
    )
    assert ft.c_pad == 4
    res = ft.fit(dsets)
    assert res.losses.shape[1] == 3
    for leaf in jax.tree.leaves(res.client_params):
        assert np.isfinite(np.asarray(leaf, dtype=np.float64)).all()


@pytest.mark.slow
def test_local_steps_schedule_semantics():
    """local_steps=E (VERDICT r4 #4): clients run E minibatches between
    FedAvg exchanges. The E>1 trajectory must differ from per-minibatch
    averaging, the final step always exchanges (so shared leaves end
    identical across clients), and E=1 stays the parity default."""
    dsets, _ = _datasets(2, n_docs=64)
    r_parity = FederatedTrainer(_template(), n_clients=2, seed=5).fit(dsets)
    r_local = FederatedTrainer(
        _template(), n_clients=2, seed=5, local_steps=3
    ).fit(dsets)

    beta_parity = np.asarray(r_parity.client_params["beta"])
    beta_local = np.asarray(r_local.client_params["beta"])
    assert not np.allclose(beta_parity[0], beta_local[0]), (
        "E=3 must change the trajectory vs per-minibatch averaging"
    )
    # Final forced exchange: shared leaves identical across clients.
    np.testing.assert_allclose(
        beta_local[0], beta_local[1], rtol=1e-5, atol=1e-6
    )
    # Same losses shape / schedule length as parity.
    assert r_local.losses.shape == r_parity.losses.shape


def test_local_steps_defers_exchange():
    """With E > total_steps the only exchange is the forced final one, so
    the run equals independent per-client training then one weighted
    average — pinned by recomputing that average from a no-share run."""
    dsets, _ = _datasets(2, n_docs=32)
    t = _template(num_epochs=1, dropout=0.0, batch_size=16)
    # 2 steps total; E=100 -> exchange only at the final step.
    res = FederatedTrainer(t, n_clients=2, seed=3, local_steps=100).fit(dsets)

    # Independent training: same template/seed but nothing shared.
    t2 = _template(num_epochs=1, dropout=0.0, batch_size=16)
    indep = FederatedTrainer(
        t2, n_clients=2, seed=3, grads_to_share=(), local_steps=100
    ).fit(dsets)
    w = np.array([len(d) for d in dsets], np.float32)
    expected = (
        w[0] * np.asarray(indep.client_params["beta"][0])
        + w[1] * np.asarray(indep.client_params["beta"][1])
    ) / w.sum()
    np.testing.assert_allclose(
        np.asarray(res.client_params["beta"][0]), expected,
        rtol=1e-5, atol=1e-6,
    )


def test_local_steps_validation():
    with pytest.raises(ValueError):
        FederatedTrainer(_template(), n_clients=2, local_steps=0)


def test_one_program_serves_different_dataset_sizes():
    """VERDICT r4 #8: total_weight is a runtime input, so two fits with the
    same array shapes but different sample weights reuse ONE compiled
    program (no retrace, no rebuild)."""
    a1, _ = _datasets(1, n_docs=96, seed=1)
    a2, _ = _datasets(1, n_docs=64, seed=2)
    b2, _ = _datasets(1, n_docs=32, seed=3)
    # Pad the smaller corpora to the same doc-count axis? Not needed: the
    # staged x_bow pads to max(len) per fit, so pick sizes with equal max
    # (96) and equal schedule length (3 steps/epoch at B=32).
    b1, _ = _datasets(1, n_docs=96, seed=4)
    t = _template(num_epochs=2, batch_size=32)
    tr = FederatedTrainer(t, n_clients=2)
    tr.fit([a1[0], a2[0]])  # total_weight 160
    program = tr._program
    assert program is not None
    n_entries = program._cache_size()
    tr.fit([b1[0], b2[0]])  # total_weight 128, same shapes
    assert tr._program is program
    assert program._cache_size() == n_entries, (
        "same-shape fit with a different total_weight must not retrace"
    )
