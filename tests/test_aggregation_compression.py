"""Aggregation strategies + wire compression (the federation comms PR).

Three layers:

1. raw codec invariants — roundtrips across EVERY ``ALLOWED_DTYPES`` entry
   (incl. bool, uint32, zero-size and 0-d arrays) and the bf16 wire-size
   regression (raw 2-byte payload, not a float32 upcast);
2. compression/aggregation units — spec parsing + negotiation ids, exact
   recovery where the codec is lossless, the error-feedback invariant
   (dropped mass is delivered, not lost), delta reference discipline
   (loud :class:`ReferenceMismatch`, never a mis-decode), FedAvg
   bit-for-bit vs the historical inline average, adaptive-aggregator state
   roundtrips through ``FederationCheckpointer``;
3. end-to-end federations over localhost gRPC — 3 clients converging under
   ``fedadam`` and under ``delta+topk+fp16`` compression with a >2x
   measured wire reduction, and codec-mismatch joins failing loudly.
"""

import threading

import numpy as np
import pytest

from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.federation import codec
from gfedntm_tpu.federation.aggregation import (
    AGGREGATORS,
    FedAvg,
    make_aggregator,
    weighted_mean,
)
from gfedntm_tpu.federation.client import Client
from gfedntm_tpu.federation.compression import (
    DownlinkDecoder,
    DownlinkEncoder,
    ReferenceMismatch,
    UplinkDecoder,
    UplinkEncoder,
    WireCodec,
)
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.federation.server import FederatedServer
from gfedntm_tpu.utils.observability import MetricsLogger


# ---- 1. raw codec: every allowed dtype roundtrips ---------------------------

def _sample_array(dtype: str, rng) -> list:
    """Representative arrays per dtype: regular, 0-d, and zero-size."""
    if dtype == "bool":
        base = rng.integers(0, 2, size=(3, 4)).astype(bool)
    elif dtype in ("int32", "int64", "uint32"):
        base = rng.integers(0, 1000, size=(3, 4)).astype(dtype)
    elif dtype == "bfloat16":
        import ml_dtypes

        base = rng.normal(size=(3, 4)).astype(ml_dtypes.bfloat16)
    else:
        base = rng.normal(size=(3, 4)).astype(dtype)
    return [
        base,
        base.reshape(-1)[0].reshape(()),  # 0-d scalar
        base[:0],                         # zero-size, shape (0, 4)
    ]


@pytest.mark.parametrize("dtype", sorted(codec.ALLOWED_DTYPES))
def test_record_roundtrip_every_allowed_dtype(dtype):
    rng = np.random.default_rng(0)
    for arr in _sample_array(dtype, rng):
        rec = codec.array_to_record("x", arr)
        out = codec.record_to_array(rec)
        assert out.dtype == arr.dtype, dtype
        assert out.shape == arr.shape, dtype
        np.testing.assert_array_equal(out, arr)


def test_bfloat16_ships_two_bytes_per_element():
    """Satellite regression: bf16 used to be upcast to float32 before
    serialization, doubling its wire size — the record must now carry the
    raw 2-byte payload and declare dtype bfloat16."""
    import ml_dtypes

    arr = np.arange(64, dtype=np.float32).astype(ml_dtypes.bfloat16)
    rec = codec.array_to_record("b", arr)
    assert rec.dtype == "bfloat16"
    assert len(rec.data) == 2 * arr.size
    out = codec.record_to_array(rec)
    assert out.dtype == arr.dtype
    np.testing.assert_array_equal(out, arr)


def test_record_to_array_rejects_compressed_records():
    """Compressed records must go through federation.compression — the raw
    codec refuses them instead of misreading the payload."""
    rec = pb.TensorRecord(
        name="x", shape=[4], dtype="float32", codec="topk",
        data=np.zeros(1, np.float32).tobytes(), aux=b"\0\0\0\0",
    )
    with pytest.raises(ValueError, match="compress"):
        codec.record_to_array(rec)


def test_record_wire_dtype_upcasts():
    """A fp16-quantized record decodes back at its logical dtype."""
    vals = np.array([0.5, -1.25, 3.0], np.float32)
    rec = pb.TensorRecord(
        name="x", shape=[3], dtype="float32", wire_dtype="float16",
        data=vals.astype(np.float16).tobytes(),
    )
    out = codec.record_to_array(rec)
    assert out.dtype == np.float32
    np.testing.assert_array_equal(out, vals)  # fp16-exact values


# ---- 2a. codec spec parsing / negotiation ids -------------------------------

class TestWireCodecSpec:
    def test_identity_spellings(self):
        for spec in (None, "", "none", "identity"):
            c = WireCodec(spec)
            assert c.identity and c.codec_id == "none" and not c.lossy

    def test_canonical_order_and_topk_implies_delta(self):
        c = WireCodec("fp16+topk:0.1")
        assert c.codec_id == "delta+topk:0.1+fp16"
        assert c.delta and c.lossy

    def test_bad_specs(self):
        for bad in ("gzip", "topk:0", "topk:1.5", "fp16+bf16"):
            with pytest.raises(ValueError):
                WireCodec(bad)

    def test_roundtrip_of_canonical_id(self):
        for spec in ("delta", "fp16", "bf16", "delta+fp16",
                     "delta+topk:0.25+bf16"):
            assert WireCodec(WireCodec(spec).codec_id).codec_id == \
                WireCodec(spec).codec_id


# ---- 2b. compression sessions: recovery + EF + reference discipline ---------

def _tensors(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "params/beta": rng.normal(size=(4, 16)).astype(np.float32),
        "params/prior_mean": rng.normal(size=(4,)).astype(np.float32),
        "batch_stats/count": np.array(7, np.int64),  # non-float rides raw
        "params/empty": np.zeros((0, 3), np.float32),
    }


def _pipe(spec):
    c = WireCodec(spec)
    return UplinkEncoder(c), UplinkDecoder(c)


def test_identity_and_quant_exact_recovery():
    x = _tensors()
    # identity: bitwise; fp16 with fp16-exact values: bitwise too
    exact = {k: (np.round(v * 4) / 4).astype(v.dtype) for k, v in x.items()}
    for spec in ("none", "fp16"):
        enc, dec = _pipe(spec)
        out = dec.decode(enc.encode(exact))
        assert set(out) == set(exact)
        for k in exact:
            assert out[k].dtype == exact[k].dtype
            np.testing.assert_array_equal(out[k], exact[k], err_msg=spec)


def test_delta_without_lossy_stages_recovers_closely():
    enc, dec = _pipe("delta")
    assert enc.residual is None  # lossless codec carries no residual
    x = _tensors()
    ref = {k: v * 0.5 for k, v in x.items()}
    enc.note_aggregate(ref, 3)
    dec.note_push(3, ref)
    bundle = enc.encode(x)
    assert bundle.ref_round == 4  # round + 1 on the wire (0 = no ref)
    out = dec.decode(bundle)
    for k in x:
        np.testing.assert_allclose(out[k], x[k], rtol=1e-6, atol=1e-7)


def test_topk_first_round_falls_back_to_dense():
    """With no reference, top-k would zero most of the model — the first
    bundle must ship dense instead."""
    enc, dec = _pipe("delta+topk:0.1")
    x = _tensors()
    bundle = enc.encode(x)
    assert bundle.ref_round == 0
    out = dec.decode(bundle)
    for k in x:
        np.testing.assert_array_equal(out[k], x[k])


def test_error_feedback_delivers_dropped_mass():
    """The EF invariant, in protocol shape: the client's state is
    overwritten by each applied aggregate, so whatever top-k dropped
    survives ONLY in the residual — and must arrive within the following
    rounds rather than being lost."""
    enc, dec = _pipe("delta+topk:0.5")
    x = {"w": np.arange(1.0, 17.0, dtype=np.float32)}
    zero = {"w": np.zeros(16, np.float32)}
    enc.note_aggregate(zero, 0)
    dec.note_push(0, zero)

    out1 = dec.decode(enc.encode(x))
    dropped = out1["w"] == 0
    assert 0 < dropped.sum() <= 8  # half the mass was withheld
    # residual holds EXACTLY what was not delivered (the EF invariant)
    np.testing.assert_array_equal(enc.residual["w"], x["w"] - out1["w"])

    # protocol turn: the aggregate the client applies IS the decoded view
    enc.note_aggregate(out1, 1)
    dec.note_push(1, out1)
    # client took no further local step: the next bundle is pure residual
    out2 = dec.decode(enc.encode(out1))
    np.testing.assert_array_equal(out2["w"], x["w"] - out1["w"] + out1["w"])
    np.testing.assert_array_equal(enc.residual["w"], np.zeros(16, np.float32))


def test_reference_mismatch_fails_loudly():
    enc, dec = _pipe("delta+fp16")
    x = _tensors()
    ref = {k: v * 0.9 for k, v in x.items()}
    enc.note_aggregate(ref, 5)
    bundle = enc.encode(x)
    with pytest.raises(ReferenceMismatch):
        dec.decode(bundle)  # decoder never saw round 5's broadcast


def test_uplink_reference_cache_evicts_oldest():
    c = WireCodec("delta")
    dec = UplinkDecoder(c, max_refs=2)
    for r in range(4):
        dec.note_push(r, {"w": np.full(3, float(r), np.float32)})
    enc = UplinkEncoder(c)
    enc.note_aggregate({"w": np.zeros(3, np.float32)}, 0)
    with pytest.raises(ReferenceMismatch):
        dec.decode(enc.encode({"w": np.ones(3, np.float32)}))
    enc.note_aggregate({"w": np.full(3, 3.0, np.float32)}, 3)
    out = dec.decode(enc.encode({"w": np.full(3, 3.5, np.float32)}))
    np.testing.assert_allclose(out["w"], 3.5, rtol=1e-6)


def test_downlink_delta_chain_and_client_view_equality():
    """The server's cached client_view must equal bitwise what the client
    reconstructs — that equality is what makes uplink deltas decodable."""
    c = WireCodec("delta+topk:0.3+fp16")
    down_enc = DownlinkEncoder(c)
    down_dec = DownlinkDecoder(c)
    rng = np.random.default_rng(1)
    avg = {"w": rng.normal(size=(8, 8)).astype(np.float32)}
    for r in range(4):
        bundle, view = down_enc.encode(avg, round_idx=r, allow_delta=r > 0)
        applied = down_dec.decode(bundle, round_idx=r)
        for k in avg:
            np.testing.assert_array_equal(applied[k], view[k])
        avg = {"w": avg["w"] * 0.95 + 0.01}


def test_compression_shrinks_wire_bytes():
    m = MetricsLogger(validate=True)
    c = WireCodec("delta+topk:0.1+fp16")
    enc = UplinkEncoder(c, metrics=m)
    dec = UplinkDecoder(c, metrics=m)
    rng = np.random.default_rng(2)
    x = {"w": rng.normal(size=(64, 64)).astype(np.float32)}
    ref = {"w": x["w"] * 0.999}
    enc.note_aggregate(ref, 0)
    dec.note_push(0, ref)
    dec.decode(enc.encode(x))
    snap = m.registry.snapshot()
    raw = snap["uncompressed_bytes_sent"]["value"]
    wire = snap["compressed_bytes_sent"]["value"]
    assert wire < raw / 4
    assert snap["compression_ratio_sent"]["value"] > 4


# ---- 2c. aggregators --------------------------------------------------------

def _snapshots(seed=0, n=3):
    rng = np.random.default_rng(seed)
    keys = ("params/beta", "params/prior_mean")
    shapes = {(k): (5, 11) if "beta" in k else (5,) for k in keys}
    return [
        (
            float(rng.integers(10, 200)),
            {k: rng.normal(size=shapes[k]).astype(np.float32) for k in keys},
        )
        for _ in range(n)
    ]


def test_fedavg_bitwise_matches_inline_path():
    """Acceptance: with --aggregator fedavg the round average must be
    numerically IDENTICAL to the historical inline expression."""
    snapshots = _snapshots()
    # the exact expression (and operand order) server.py used inline
    round_weight = float(sum(w for w, _ in snapshots))
    keys = snapshots[0][1].keys()
    inline = {
        k: sum(w * s[k] for w, s in snapshots) / round_weight for k in keys
    }
    current = {k: np.zeros_like(v) for k, v in snapshots[0][1].items()}
    for out in (
        FedAvg().aggregate(snapshots, current),
        weighted_mean(snapshots),
    ):
        assert set(out) == set(inline)
        for k in inline:
            np.testing.assert_array_equal(out[k], inline[k])


def test_make_aggregator_names_and_errors():
    for name in ("fedavg", "fedavgm", "fedadam", "fedyogi"):
        assert make_aggregator(name).name == name
    assert set(AGGREGATORS) == {"fedavg", "fedavgm", "fedadam", "fedyogi"}
    with pytest.raises(ValueError):
        make_aggregator("fedprox")
    inst = FedAvg()
    assert make_aggregator(inst) is inst


def test_fedavgm_accumulates_momentum():
    ag = make_aggregator("fedavgm", server_lr=1.0, beta=0.5)
    snaps = [(1.0, {"w": np.ones(4, np.float32)})]
    cur = {"w": np.zeros(4, np.float32)}
    out1 = ag.aggregate(snaps, cur)            # m = 1      -> x = 1
    out2 = ag.aggregate(snaps, out1)           # m = .5*1+0 -> x = 1.5
    np.testing.assert_allclose(out1["w"], 1.0)
    np.testing.assert_allclose(out2["w"], 1.5)


def test_adaptive_aggregators_state_roundtrip():
    for name in ("fedavgm", "fedadam", "fedyogi"):
        ag = make_aggregator(name)
        snaps = _snapshots(seed=3)
        cur = {k: np.zeros_like(v) for k, v in snaps[0][1].items()}
        out = ag.aggregate(snaps, cur)
        state = ag.state_dict()
        assert state  # stateful
        twin = make_aggregator(name)
        twin.load_state_dict(state)
        a = ag.aggregate(snaps, out)
        b = twin.aggregate(snaps, out)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k], err_msg=name)


def test_stateless_aggregator_rejects_foreign_state():
    with pytest.raises(ValueError):
        FedAvg().load_state_dict({"m::w": np.zeros(2)})


def test_aggregator_state_survives_checkpointer(tmp_path):
    from gfedntm_tpu.train.checkpoint import FederationCheckpointer

    ag = make_aggregator("fedadam")
    snaps = _snapshots(seed=4)
    cur = {k: np.zeros_like(v) for k, v in snaps[0][1].items()}
    avg = ag.aggregate(snaps, cur)

    ckpt = FederationCheckpointer(str(tmp_path))
    ckpt.save_round(
        12, avg, membership=[], vocab=["a", "b"],
        extra={"aggregator": ag.name},
        aggregator_state=ag.state_dict(),
    )
    state = ckpt.load_aggregator_state()
    assert state is not None
    round_idx, arrays = state
    assert round_idx == 12
    twin = make_aggregator("fedadam")
    twin.load_state_dict(arrays)
    a = ag.aggregate(snaps, avg)
    b = twin.aggregate(snaps, avg)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])
    ckpt.close()


def test_checkpointer_clears_stale_aggregator_state(tmp_path):
    """A later stateless-aggregator save must remove the previous
    configuration's state file so a resume cannot load foreign moments."""
    from gfedntm_tpu.train.checkpoint import FederationCheckpointer

    avg = {"w": np.ones(3, np.float32)}
    ckpt = FederationCheckpointer(str(tmp_path))
    ckpt.save_round(5, avg, membership=[],
                    aggregator_state={"m::w": np.ones(3, np.float32)})
    assert ckpt.load_aggregator_state() is not None
    ckpt.save_round(10, avg, membership=[], aggregator_state=None)
    assert ckpt.load_aggregator_state() is None
    ckpt.close()


# ---- 3. end-to-end federations over localhost gRPC --------------------------

def _make_corpora(n_clients: int, docs: int = 18, seed: int = 0):
    rng = np.random.default_rng(seed)
    words = [f"word{i:03d}" for i in range(90)]
    corpora = []
    for c in range(n_clients):
        lo = 20 * c
        corpora.append(RawCorpus(documents=[
            " ".join(rng.choice(words[lo:lo + 60], size=25))
            for _ in range(docs)
        ]))
    return corpora


_MODEL_KW = dict(
    n_components=3, hidden_sizes=(8, 8), batch_size=8, num_epochs=2, seed=0,
)


def _run_federation(tmp_path, metrics, aggregator="fedavg",
                    wire_codec="none", n_clients=3):
    server = FederatedServer(
        min_clients=n_clients, family="avitm", model_kwargs=dict(_MODEL_KW),
        max_iters=300, save_dir=str(tmp_path / "server"),
        aggregator=aggregator, wire_codec=wire_codec, metrics=metrics,
    )
    addr = server.start("[::]:0")
    clients = [
        Client(
            client_id=c + 1, corpus=corp, server_address=addr,
            max_features=80, save_dir=str(tmp_path / f"client{c + 1}"),
            metrics=metrics,
        )
        for c, corp in enumerate(_make_corpora(n_clients))
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    finished = server.wait_done(timeout=240)
    for t in threads:
        t.join(timeout=30)
    assert finished, f"{aggregator}/{wire_codec} federation did not finish"
    for c in clients:
        assert c.stopped.is_set() and c.results is not None
        assert c.stepper.finished
        assert np.isfinite(c.results["betas"]).all()
    assert np.isfinite(server.global_betas).all()
    server.stop()
    for c in clients:
        c.shutdown()
    return server, clients


def test_e2e_fedadam_three_clients_converges(tmp_path):
    """Acceptance: a chaos-free 3-client federation converges under the
    fedadam server optimizer (completes its epochs, finite artifacts)."""
    m = MetricsLogger(validate=True)
    server, clients = _run_federation(tmp_path, m, aggregator="fedadam")
    assert server.aggregator.name == "fedadam"
    assert server.aggregator.state_dict()  # moments actually accumulated
    losses = [c.stepper.epoch_losses[-1] for c in clients]
    assert all(np.isfinite(losses))


@pytest.mark.parametrize("aggregator", ["fedavgm", "fedyogi"])
def test_e2e_remaining_aggregators_converge(tmp_path, aggregator):
    """Every shipped aggregator completes a 2-client federation with
    finite artifacts and accumulated server-optimizer state."""
    m = MetricsLogger(validate=True)
    server, _clients = _run_federation(
        tmp_path, m, aggregator=aggregator, n_clients=2
    )
    assert server.aggregator.name == aggregator
    assert server.aggregator.state_dict()


def test_e2e_topk_compression_with_error_feedback(tmp_path):
    """Acceptance: 3 clients converge under delta+topk+fp16 with
    client-side error feedback, and telemetry reports a >2x wire
    reduction for the run."""
    m = MetricsLogger(validate=True)
    server, clients = _run_federation(
        tmp_path, m, wire_codec="delta+topk:0.1+fp16"
    )
    # every client negotiated the canonical codec id
    negotiated = m.events("codec_negotiated")
    assert {e["codec"] for e in negotiated} == {"delta+topk:0.1+fp16"}
    assert len(negotiated) == 3
    # error feedback actually engaged client-side
    assert any(
        c._uplink is not None and c._uplink.residual for c in clients
    )
    snap = m.registry.snapshot()
    raw = snap["uncompressed_bytes_sent"]["value"]
    wire = snap["compressed_bytes_sent"]["value"]
    assert wire > 0 and raw / wire > 2.0, (raw, wire)
    assert snap["compression_ratio_sent"]["value"] > 2.0
    # decode path verified end-to-end: recv ratio compresses too
    assert snap["compression_ratio_recv"]["value"] > 2.0


def test_e2e_fedavg_identity_unchanged_defaults(tmp_path):
    """Default server (fedavg + identity codec): StepReply/Aggregate
    bundles stay raw (self-contained) and negotiation yields 'none'."""
    m = MetricsLogger(validate=True)
    server, clients = _run_federation(tmp_path, m, n_clients=2)
    assert server.wire_codec.identity
    assert {e["codec"] for e in m.events("codec_negotiated")} == {"none"}
    assert all(c._uplink is None and c._downlink is None for c in clients)


def test_codec_mismatch_rejected_at_join():
    """Mixed fleets must fail loudly at ReadyForTraining (Ack code 2)."""
    m = MetricsLogger(validate=True)
    server = FederatedServer(
        min_clients=1, family="avitm", model_kwargs=dict(_MODEL_KW),
        wire_codec="delta+fp16", metrics=m,
    )
    ack = server.ReadyForTraining(
        pb.JoinRequest(client_id=3, address="localhost:1", codec_id="none"),
        None,
    )
    assert ack.code == 2
    assert "delta+fp16" in ack.detail
    assert len(server.federation) == 0  # turned away before registration
    assert m.events("codec_mismatch")


def test_client_explicit_codec_mismatch_raises():
    """A client configured with an explicit codec refuses a federation
    advertising a different one (fail loudly, never mis-decode)."""
    client = Client(
        client_id=1, corpus=_make_corpora(1)[0],
        server_address="localhost:1", wire_codec="fp16",
    )
    with pytest.raises(ValueError, match="mismatch"):
        client._negotiate_codec("delta+topk:0.1+fp16")


def test_client_auto_adopts_server_codec():
    client = Client(
        client_id=1, corpus=_make_corpora(1)[0],
        server_address="localhost:1",
    )
    client._negotiate_codec("delta+topk:0.5+bf16")
    assert client._codec.codec_id == "delta+topk:0.5+bf16"
    assert client._uplink is not None and client._downlink is not None


@pytest.mark.slow
def test_e2e_fedadam_resume_keeps_optimizer_state(tmp_path):
    """--resume continuity for the server optimizer: a fedadam federation
    checkpointed mid-run restores with its moments, not cold state."""
    m = MetricsLogger(validate=True)
    server = FederatedServer(
        min_clients=1, family="avitm", model_kwargs=dict(_MODEL_KW),
        max_iters=300, save_dir=str(tmp_path / "server"),
        aggregator="fedadam", checkpoint_every=2, metrics=m,
    )
    addr = server.start("[::]:0")
    client = Client(
        client_id=1, corpus=_make_corpora(1, docs=30)[0],
        server_address=addr, max_features=80,
        save_dir=str(tmp_path / "c1"),
    )
    t = threading.Thread(target=client.run, daemon=True)
    t.start()
    assert server.wait_done(timeout=240)
    t.join(timeout=30)
    saved_state = server.aggregator.state_dict()
    assert saved_state
    server.stop()
    client.shutdown()

    server2 = FederatedServer(
        min_clients=1, family="avitm", model_kwargs=dict(_MODEL_KW),
        max_iters=300, save_dir=str(tmp_path / "server"),
        aggregator="fedadam",
    )
    restored_round = server2.restore_from_checkpoint()
    assert restored_round > 0
    state2 = server2.aggregator.state_dict()
    assert set(state2) == set(saved_state)
    for k in saved_state:
        np.testing.assert_array_equal(state2[k], saved_state[k])

    # a config change falls back to fresh state with a warning, not a load
    server3 = FederatedServer(
        min_clients=1, family="avitm", model_kwargs=dict(_MODEL_KW),
        max_iters=300, save_dir=str(tmp_path / "server"),
        aggregator="fedyogi",
    )
    server3.restore_from_checkpoint()
    assert not server3.aggregator.state_dict()
