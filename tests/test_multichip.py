"""Multi-chip data-sharded training (ISSUE 12).

Pins the tentpole contracts of the mesh-enabled local-training path on
the forced 8-virtual-device CPU mesh (conftest):

- ``pad_batch_axis`` / ``shard_docs`` mechanics (one padded shape, inert
  pad rows, per-device doc sharding);
- ``fit_data_sharded`` parity with the single-device ``model.fit`` —
  same seed, 8-device mesh vs 1 device, betas within 1e-4 after E
  epochs — plus donation safety (the model's own carried state survives
  a donating call; GL003-clean by construction via the
  ``copy_for_donation`` seam);
- the mesh-enabled ``FederatedStepper`` (a federation client's local
  step) against the meshless stepper;
- live FLOPs/MFU accounting (``utils.flops``), including the
  scan-body-counted-ONCE property of XLA's cost analysis that the
  accounting depends on;
- the ``--mesh_devices`` CLI debug knob.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gfedntm_tpu.data.datasets import BowDataset
from gfedntm_tpu.parallel.mesh import (
    ensure_virtual_devices,
    make_param_mesh,
)
from gfedntm_tpu.parallel.sharded import fit_data_sharded, shard_docs
from gfedntm_tpu.train.steps import pad_batch_axis

VOCAB = 120
TOPICS = 4


def _dataset(docs=192, vocab=VOCAB, seed=0):
    rng = np.random.default_rng(seed)
    return BowDataset(
        X=rng.integers(0, 3, size=(docs, vocab)).astype(np.float32),
        idx2token={i: f"wd{i}" for i in range(vocab)},
    )


def _model(num_epochs=3, batch_size=32, seed=7):
    from gfedntm_tpu.models.avitm import AVITM

    return AVITM(
        input_size=VOCAB, n_components=TOPICS, hidden_sizes=(16, 16),
        batch_size=batch_size, num_epochs=num_epochs, lr=2e-3, seed=seed,
        fused_decoder=False,
    )


class TestPadBatchAxis:
    def test_pads_to_multiple_with_masked_rows(self):
        idx = np.arange(12, dtype=np.int32).reshape(2, 6)
        mask = np.ones((2, 6), np.float32)
        idx_p, mask_p = pad_batch_axis(idx, mask, 8)
        assert idx_p.shape == (2, 8) and mask_p.shape == (2, 8)
        # Kept rows byte-identical, pad rows masked no-ops on doc 0.
        np.testing.assert_array_equal(idx_p[:, :6], idx)
        np.testing.assert_array_equal(mask_p[:, :6], mask)
        assert (idx_p[:, 6:] == 0).all() and (mask_p[:, 6:] == 0).all()

    def test_noop_when_already_divisible(self):
        idx = np.arange(16, dtype=np.int32).reshape(2, 8)
        mask = np.ones((2, 8), np.float32)
        idx_p, mask_p = pad_batch_axis(idx, mask, 8)
        assert idx_p is idx and mask_p is mask


class TestShardDocs:
    def test_doc_axis_sharded_and_padded(self):
        mesh = make_param_mesh(axis_name="data")
        n_dev = int(mesh.devices.size)
        data = {
            "x": np.ones((n_dev * 2 + 1, 5), np.float32),
            "labels": None,
        }
        out = shard_docs(data, mesh, "data")
        assert out["labels"] is None
        # Padded up to the next multiple of the mesh and actually sharded.
        assert out["x"].shape[0] == n_dev * 3
        assert float(np.asarray(out["x"]).sum()) == (n_dev * 2 + 1) * 5
        spec = out["x"].sharding.spec
        assert spec[0] == "data"


class TestMesh:
    def test_n_devices_caps_mesh(self):
        mesh = make_param_mesh(axis_name="data", n_devices=2)
        assert int(mesh.devices.size) == 2

    def test_n_devices_out_of_range(self):
        with pytest.raises(ValueError):
            make_param_mesh(n_devices=len(jax.devices()) + 1)
        with pytest.raises(ValueError):
            make_param_mesh(n_devices=0)

    def test_ensure_virtual_devices_after_init_reports_live_count(self):
        # The backend is initialized (conftest forced 8 devices), so the
        # bootstrap must not touch the env and must report what exists.
        assert ensure_virtual_devices(16) == len(jax.devices())


class TestFlops:
    def test_measure_program_flops_positive(self):
        from gfedntm_tpu.utils.flops import measure_program_flops

        prog = jax.jit(
            lambda a: jnp.matmul(
                a, a, precision=jax.lax.Precision.HIGHEST
            )
        )
        x = jnp.ones((64, 64), jnp.float32)
        flops = measure_program_flops(prog, x)
        assert flops is not None and flops >= 2 * 64 * 64 * 64 * 0.9

    def test_scan_body_counted_once(self):
        """The accounting contract trainer.fit / fit_data_sharded rely
        on: XLA's cost analysis counts a scan body ONCE regardless of
        trip count, so a length-S step-scan program's measured flops
        approximate one step, not S. If a jax upgrade changes this, the
        MFU call sites must be re-derived — fail here, loudly."""
        from gfedntm_tpu.utils.flops import measure_program_flops

        def body(c, _):
            return (
                jnp.matmul(c, c, precision=jax.lax.Precision.HIGHEST),
                None,
            )

        def scan_n(n):
            return jax.jit(
                lambda x: jax.lax.scan(body, x, None, length=n)[0]
            )

        x = jnp.ones((64, 64), jnp.float32)
        f1 = measure_program_flops(scan_n(1), x)
        f10 = measure_program_flops(scan_n(10), x)
        assert f1 is not None and f10 is not None
        assert f10 < 2.0 * f1  # NOT ~10x: the body is counted once

    def test_mfu_math_and_guards(self):
        from gfedntm_tpu.utils.flops import mfu

        assert mfu(1e9, 1.0, 2, 1e9) == pytest.approx(0.5)
        assert mfu(None, 1.0, 2, 1e9) is None
        assert mfu(1e9, 0.0, 2, 1e9) is None
        assert mfu(1e9, 1.0, 2, None) is None

    def test_resolve_peak_cpu_is_measured(self):
        from gfedntm_tpu.utils.flops import resolve_peak_flops_per_device

        peak, source = resolve_peak_flops_per_device("cpu")
        assert peak and peak > 0 and source == "measured-matmul-probe"
        peak_tpu, source_tpu = resolve_peak_flops_per_device("tpu")
        assert source_tpu == "nominal-spec" and peak_tpu == 197.0e12


class TestFitDataSharded:
    def test_parity_8dev_vs_single_device(self):
        """Same seed, 8-device host mesh vs the single-device model.fit:
        betas within 1e-4 after E epochs (the ISSUE 12 acceptance bar —
        the only difference is reduction order across the mesh)."""
        ds = _dataset()
        ref = _model()
        ref.fit(ds)
        betas_ref = np.asarray(ref.best_components)

        sharded = _model()
        mesh = make_param_mesh(axis_name="data", n_devices=8)
        summary = fit_data_sharded(sharded, ds, mesh=mesh)
        betas_sh = np.asarray(sharded.best_components)

        assert np.max(np.abs(betas_ref - betas_sh)) < 1e-4
        assert summary["devices"] == 8
        assert summary["epochs_run"] == 3
        assert len(sharded.epoch_losses) == 3
        assert np.isfinite(sharded.epoch_losses).all()
        # Losses match the single-device trajectory too (not just betas).
        np.testing.assert_allclose(
            sharded.epoch_losses, ref.epoch_losses, rtol=1e-4
        )

    def test_single_device_mesh_matches_tightly(self):
        ds = _dataset()
        ref = _model(num_epochs=2)
        ref.fit(ds)
        one = _model(num_epochs=2)
        fit_data_sharded(one, ds, mesh=make_param_mesh(
            axis_name="data", n_devices=1,
        ))
        np.testing.assert_allclose(
            np.asarray(ref.best_components),
            np.asarray(one.best_components),
            atol=1e-6,
        )

    def test_summary_carries_throughput_accounting(self):
        ds = _dataset(docs=96)
        m = _model(num_epochs=3)
        summary = fit_data_sharded(m, ds, n_devices=4)
        assert summary["devices"] == 4
        assert summary["docs_per_s"] and summary["docs_per_s"] > 0
        assert summary["docs_per_s_per_device"] == pytest.approx(
            summary["docs_per_s"] / 4, rel=0.01
        )
        assert summary["compile_s"] > 0
        assert summary["batch_pad"] % 4 == 0
        # Live FLOPs accounting: per-epoch = per-step x steps.
        if summary["flops_per_step"] is not None:
            assert summary["flops_per_epoch"] == pytest.approx(
                summary["flops_per_step"] * summary["steps_per_epoch"]
            )
            assert summary["mfu"] is None or summary["mfu"] > 0
        assert summary["peak_flops_source"] in (
            "measured-matmul-probe", "nominal-spec", "caller",
        )

    def test_donation_safety_state_survives(self):
        """The donating epoch program must never consume the MODEL's own
        arrays: the copy_for_donation seam hands it a copy, so the
        caller's state stays readable and a second fit from the updated
        model state works (the GL003 shape, behaviorally)."""
        ds = _dataset(docs=96)
        m = _model(num_epochs=2)
        params_before = m.params
        fit_data_sharded(m, ds, n_devices=8, donate=True)
        # The pre-fit param arrays are still materializable (donation on
        # CPU is a no-op, on accelerators the copy seam protects them) …
        leaves = jax.tree_util.tree_leaves(params_before)
        assert all(np.isfinite(np.asarray(leaf)).all() for leaf in leaves)
        # … and the model's post-fit state supports ANOTHER donating fit.
        fit_data_sharded(m, ds, n_devices=8, donate=True)
        assert np.isfinite(np.asarray(m.best_components)).all()

    def test_copy_for_donation_is_independent(self):
        from gfedntm_tpu.train.optimizers import copy_for_donation

        tree = {"a": jnp.ones((4,)), "b": None, "c": "keep"}
        copy = copy_for_donation(tree)
        assert copy["b"] is None and copy["c"] == "keep"
        assert copy["a"] is not tree["a"]
        np.testing.assert_array_equal(
            np.asarray(copy["a"]), np.asarray(tree["a"])
        )

    def test_fused_decoder_rejected(self):
        ds = _dataset(docs=64)
        m = _model(num_epochs=1)
        m.module.fused_decoder = True
        with pytest.raises(ValueError, match="fused"):
            fit_data_sharded(m, ds, n_devices=2)

    def test_dshard_fused_guard_in_steps(self):
        from gfedntm_tpu.train.steps import (
            build_train_epoch,
            build_train_step,
        )

        m = _model(num_epochs=1)
        m.module.fused_decoder = True
        mesh = make_param_mesh(axis_name="data", n_devices=2)
        for builder in (build_train_epoch, build_train_step):
            with pytest.raises(ValueError, match="fused"):
                builder(
                    m.module, m.tx, m.family, m._beta_weight(),
                    dshard=(mesh, "data"),
                )


class TestStepperMesh:
    def test_mesh_stepper_matches_meshless(self):
        """A federation client's local step on the 8-device mesh must
        track the single-device stepper: same seed, same minibatch
        schedule (bucket-padded rows are masked no-ops), betas within
        1e-4 after a full epoch of steps."""
        from gfedntm_tpu.federated.stepper import FederatedAVITM

        ds = _dataset(docs=80)

        def mk(mesh):
            s = FederatedAVITM(_model(num_epochs=2, batch_size=32), mesh=mesh)
            s.pre_fit(ds)
            return s

        plain = mk(None)
        meshed = mk(make_param_mesh(axis_name="data", n_devices=8))
        assert meshed.mesh is not None
        # Bucket padding: every scheduled batch divides the mesh.
        assert meshed._schedule.indices.shape[1] % 8 == 0

        for _ in range(6):
            snap_plain = plain.train_mb_delta()
            snap_mesh = meshed.train_mb_delta()
            assert np.max(np.abs(
                snap_plain["params/beta"] - snap_mesh["params/beta"]
            )) < 1e-4

    def test_size1_mesh_is_single_device_path(self):
        from gfedntm_tpu.federated.stepper import FederatedAVITM

        s = FederatedAVITM(
            _model(num_epochs=1),
            mesh=make_param_mesh(axis_name="data", n_devices=1),
        )
        assert s.mesh is None  # size-1 mesh = EXACTLY the historical path


class TestCLIMeshKnob:
    def test_parser_accepts_mesh_devices(self):
        from gfedntm_tpu.cli import build_parser

        args = build_parser().parse_args(
            ["--role", "client", "--id", "1", "--mesh_devices", "8"]
        )
        assert args.mesh_devices == 8

    def test_default_is_off(self):
        from gfedntm_tpu.cli import build_parser

        args = build_parser().parse_args(["--role", "server", "--id", "0"])
        assert args.mesh_devices == 0

    def test_ensure_mesh_devices_initialized_backend(self, caplog):
        """With the backend already up (conftest), the knob must not
        crash and must warn when asked for more devices than exist."""
        import argparse
        import logging

        from gfedntm_tpu.cli import _ensure_mesh_devices

        ns = argparse.Namespace(mesh_devices=len(jax.devices()) + 4)
        with caplog.at_level(logging.WARNING):
            _ensure_mesh_devices(ns)
        assert any(
            "devices" in rec.message for rec in caplog.records
        )
