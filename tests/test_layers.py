"""MaskedBatchNorm parity vs torch nn.BatchNorm1d(affine=False)."""

import jax
import numpy as np
import pytest
import torch

from gfedntm_tpu.models.layers import MaskedBatchNorm, TorchDense


def _run_flax_bn(x_steps, train=True, mask=None):
    bn = MaskedBatchNorm()
    variables = bn.init(jax.random.PRNGKey(0), x_steps[0], use_running_average=False)
    outs = []
    for x in x_steps:
        y, mut = bn.apply(
            variables,
            x,
            use_running_average=not train,
            mask=mask,
            mutable=["batch_stats"],
        )
        variables = {**variables, **mut}
        outs.append(np.asarray(y))
    return outs, variables["batch_stats"]


def test_batchnorm_train_matches_torch(rng):
    feats = 6
    xs = [rng.normal(size=(12, feats)).astype(np.float32) for _ in range(4)]
    tbn = torch.nn.BatchNorm1d(feats, affine=False)
    tbn.train()
    t_outs = [tbn(torch.from_numpy(x)).detach().numpy() for x in xs]

    f_outs, stats = _run_flax_bn(xs, train=True)
    for f, t in zip(f_outs, t_outs):
        np.testing.assert_allclose(f, t, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(stats["running_mean"]), tbn.running_mean.numpy(), rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(stats["running_var"]), tbn.running_var.numpy(), rtol=1e-4, atol=1e-6
    )
    assert int(stats["num_batches_tracked"]) == int(tbn.num_batches_tracked)


def test_batchnorm_eval_matches_torch(rng):
    feats = 5
    warm = [rng.normal(size=(8, feats)).astype(np.float32) for _ in range(3)]
    x_eval = rng.normal(size=(8, feats)).astype(np.float32)

    tbn = torch.nn.BatchNorm1d(feats, affine=False)
    tbn.train()
    for x in warm:
        tbn(torch.from_numpy(x))
    tbn.eval()
    t_out = tbn(torch.from_numpy(x_eval)).detach().numpy()

    bn = MaskedBatchNorm()
    variables = bn.init(jax.random.PRNGKey(0), warm[0], use_running_average=False)
    for x in warm:
        _, mut = bn.apply(
            variables, x, use_running_average=False, mutable=["batch_stats"]
        )
        variables = {**variables, **mut}
    y = bn.apply(variables, x_eval, use_running_average=True)
    np.testing.assert_allclose(np.asarray(y), t_out, rtol=1e-4, atol=1e-5)


def test_masked_batchnorm_equals_short_batch(rng):
    """Padded+masked batch stats must equal torch on the unpadded batch."""
    feats = 4
    real, pad = 9, 16
    x_real = rng.normal(size=(real, feats)).astype(np.float32)
    x_pad = np.zeros((pad, feats), np.float32)
    x_pad[:real] = x_real
    mask = np.zeros(pad, np.float32)
    mask[:real] = 1.0

    tbn = torch.nn.BatchNorm1d(feats, affine=False)
    tbn.train()
    t_out = tbn(torch.from_numpy(x_real)).detach().numpy()

    outs, stats = _run_flax_bn([x_pad], train=True, mask=mask)
    np.testing.assert_allclose(outs[0][:real], t_out, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(stats["running_mean"]), tbn.running_mean.numpy(), rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(stats["running_var"]), tbn.running_var.numpy(), rtol=1e-4, atol=1e-6
    )


def test_torch_dense_matches_torch_linear(rng):
    """Same weights -> same outputs (kernel is torch weight transposed)."""
    lin = torch.nn.Linear(7, 3)
    x = rng.normal(size=(5, 7)).astype(np.float32)
    t_out = lin(torch.from_numpy(x)).detach().numpy()

    dense = TorchDense(3)
    variables = dense.init(jax.random.PRNGKey(0), x)
    variables = {
        "params": {
            "kernel": lin.weight.detach().numpy().T,
            "bias": lin.bias.detach().numpy(),
        }
    }
    y = dense.apply(variables, x)
    np.testing.assert_allclose(np.asarray(y), t_out, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
class TestBfloat16Compute:
    """compute_dtype='bfloat16' runs the matmuls in bf16 with f32 params and
    BatchNorm stats — must train finite and land near the f32 trajectory."""

    def _fit(self, compute_dtype):
        import numpy as np

        from gfedntm_tpu.data.datasets import BowDataset
        from gfedntm_tpu.models.avitm import AVITM

        rng = np.random.default_rng(7)
        V = 120
        X = rng.integers(0, 3, size=(24, V)).astype(np.float32)
        data = BowDataset(X=X, idx2token={i: f"wd{i}" for i in range(V)})
        model = AVITM(
            input_size=V, n_components=4, hidden_sizes=(16, 16),
            batch_size=8, num_epochs=2, seed=0, fused_decoder=False,
            compute_dtype=compute_dtype,
        )
        model.fit(data)
        return model

    def test_bf16_trains_finite_with_f32_state(self):
        import jax.numpy as jnp
        import numpy as np

        model = self._fit("bfloat16")
        assert np.isfinite(np.asarray(model.params["beta"])).all()
        # parameters and BN stats stay float32
        assert model.params["beta"].dtype == jnp.float32
        bn = model.batch_stats["beta_batchnorm"]
        assert bn["running_mean"].dtype == jnp.float32

    def test_bf16_near_f32_trajectory(self):
        import numpy as np

        beta_bf16 = np.asarray(self._fit("bfloat16").params["beta"])
        beta_f32 = np.asarray(self._fit("float32").params["beta"])
        # loose: bf16 matmuls round, but two epochs shouldn't diverge wildly
        corr = np.corrcoef(beta_bf16.ravel(), beta_f32.ravel())[0, 1]
        assert corr > 0.98
