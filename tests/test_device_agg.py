"""Device-resident aggregation data plane (tier-1, ISSUE 6).

Parity is the contract (README "Device-resident aggregation"): the numpy
implementations in ``aggregation.py``/``sanitize.py`` are the oracle, and
the device backend — stacked snapshots, ``shard_map``-sharded gate
statistics and robust estimators — must reproduce them: weighted mean
bitwise in float32, trimmed mean / median / Krum to 1e-6, and identical
UpdateGate admission decisions. The suite runs on the 8-virtual-device
CPU mesh (conftest), so the real mesh path is the code under test even
without an accelerator.
"""

import threading

import numpy as np
import pytest

from gfedntm_tpu.cli import build_parser
from gfedntm_tpu.federation import codec
from gfedntm_tpu.federation.aggregation import (
    Krum,
    Median,
    TrimmedMean,
    WeightedMean,
    krum_select,
    make_aggregator,
    weighted_mean,
)
from gfedntm_tpu.federation.device_agg import (
    DeviceAggEngine,
    FlatPlane,
    StackedRound,
    stack_round,
)
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.federation.registry import DROPPED, SUSPECT
from gfedntm_tpu.federation.sanitize import UpdateGate, update_norm
from gfedntm_tpu.federation.server import FederatedServer, build_template_model
from gfedntm_tpu.utils.observability import MetricsLogger

MODEL_KWARGS = dict(
    n_components=3, hidden_sizes=(8,), batch_size=8, num_epochs=2, seed=0,
)

TEMPLATE = {
    "a": np.zeros((6, 9), np.float32),
    "b": np.zeros((17,), np.float32),
    "n": np.zeros((), np.int32),  # num_batches_tracked-style int scalar
}


@pytest.fixture(scope="module")
def engine():
    return DeviceAggEngine()


@pytest.fixture(scope="module")
def plane():
    return FlatPlane(TEMPLATE)


def _snap(rng, scale=1.0, around=None):
    base = around or {k: np.zeros_like(v) for k, v in TEMPLATE.items()}
    return {
        "a": (base["a"] + scale * rng.normal(size=(6, 9))).astype(np.float32),
        "b": (base["b"] + scale * rng.normal(size=(17,))).astype(np.float32),
        "n": np.int32(rng.integers(0, 7)),
    }


def _pairs(n=5, seed=0, weights=None):
    rng = np.random.default_rng(seed)
    weights = weights or [3.0, 1.0, 2.5, 4.0, 1.5, 2.0, 0.5, 6.0][:n]
    return [(float(w), _snap(rng)) for w in weights]


def _assert_estimates_equal(dev, ref, *, bitwise_f32=False):
    assert set(dev) == set(ref)
    for k in ref:
        r, d = np.asarray(ref[k]), np.asarray(dev[k])
        assert r.dtype == d.dtype, (k, r.dtype, d.dtype)
        assert r.shape == d.shape
        if bitwise_f32 and r.dtype == np.float32:
            assert np.array_equal(
                r.view(np.uint32), d.view(np.uint32)
            ), (k, float(np.max(np.abs(r - d))))
        else:
            np.testing.assert_allclose(
                d.astype(np.float64), r.astype(np.float64),
                rtol=2e-6, atol=2e-6, err_msg=k,
            )


# ---- flat plane --------------------------------------------------------------

class TestFlatPlane:
    def test_layout_and_roundtrip(self, plane):
        assert plane.keys == sorted(TEMPLATE)  # the _stacked/Krum order
        assert plane.dim == 6 * 9 + 17 + 1
        assert plane.non_f32_keys == ["n"]
        snap = _snap(np.random.default_rng(3))
        vec = plane.flatten(snap)
        back = plane.unflatten(vec)
        for k in TEMPLATE:
            assert np.asarray(back[k]).dtype == np.asarray(snap[k]).dtype
            np.testing.assert_array_equal(
                np.asarray(back[k], np.float64),
                np.asarray(snap[k], np.float64),
            )

    def test_stack_pads_to_mesh_multiple(self, engine, plane):
        mat = engine.stack(plane, [s for _w, s in _pairs(3)])
        assert mat.shape[0] == 3
        assert mat.shape[1] % engine.n_shards == 0
        assert mat.shape[1] >= plane.dim


# ---- estimator parity --------------------------------------------------------

class TestEstimatorParity:
    def _stacked(self, engine, plane, pairs):
        return stack_round(engine, plane, pairs)

    def test_weighted_mean_bitwise_f32(self, engine, plane):
        pairs = _pairs(5)
        sr = self._stacked(engine, plane, pairs)
        _assert_estimates_equal(
            WeightedMean()(sr), weighted_mean(pairs), bitwise_f32=True,
        )

    def test_weighted_mean_weights_matter_and_int_semantics(
        self, engine, plane
    ):
        # Distinct, uneven weights: the device path must use them in the
        # same order and rounding as the numpy chain (bitwise), and the
        # int32 key must keep weighted_mean's numpy dtype semantics
        # (int tensors average to float64 — no cast back).
        pairs = _pairs(6, seed=9, weights=[10.0, 0.25, 7.5, 1.0, 3.0, 0.5])
        sr = self._stacked(engine, plane, pairs)
        dev, ref = WeightedMean()(sr), weighted_mean(pairs)
        _assert_estimates_equal(dev, ref, bitwise_f32=True)
        assert np.asarray(ref["n"]).dtype == np.float64
        assert np.asarray(dev["n"]).dtype == np.float64

    @pytest.mark.parametrize("n,frac", [(4, 0.25), (5, 0.2), (8, 0.3)])
    def test_trimmed_mean_parity(self, engine, plane, n, frac):
        pairs = _pairs(n, seed=n)
        sr = self._stacked(engine, plane, pairs)
        est = TrimmedMean(frac)
        _assert_estimates_equal(est(sr), est(pairs))

    @pytest.mark.parametrize("n", [3, 4, 5, 8])
    def test_median_parity(self, engine, plane, n):
        pairs = _pairs(n, seed=10 + n)
        sr = self._stacked(engine, plane, pairs)
        _assert_estimates_equal(Median()(sr), Median()(pairs))

    def test_median_even_cohort_averages_middles(self, engine, plane):
        pairs = _pairs(4, seed=2)
        sr = self._stacked(engine, plane, pairs)
        _assert_estimates_equal(Median()(sr), Median()(pairs))

    def test_krum_parity_and_neighbor_selection(self, engine, plane):
        rng = np.random.default_rng(7)
        honest = [(2.0, _snap(rng, scale=0.1)) for _ in range(4)]
        attacker = (9.0, _snap(rng, scale=50.0))
        pairs = honest + [attacker]
        sr = self._stacked(engine, plane, pairs)
        est = Krum(1)
        _assert_estimates_equal(est(sr), est(pairs))
        # Selection parity, directly: the device gram-identity distances
        # must pick the same neighbors the numpy flat distances pick.
        flat = np.stack([
            np.concatenate([
                np.asarray(s[k], np.float32).ravel() for k in sorted(s)
            ]) for _w, s in pairs
        ])
        sq = np.einsum("ij,ij->i", flat, flat)
        d2_np = sq[:, None] + sq[None, :] - 2.0 * (flat @ flat.T)
        chosen_np = krum_select(d2_np, len(pairs), 1)
        chosen_dev = krum_select(engine.krum_d2(sr), len(pairs), 1)
        np.testing.assert_array_equal(chosen_np, chosen_dev)
        assert len(pairs) - 1 not in chosen_np  # attacker never selected

    def test_krum_never_selects_nonfinite_row(self, engine, plane):
        rng = np.random.default_rng(8)
        pairs = [(1.0, _snap(rng, scale=0.1)) for _ in range(4)]
        bad = _snap(rng, scale=0.1)
        bad["a"] = bad["a"].copy()
        bad["a"][0, 0] = np.nan
        pairs.append((5.0, bad))
        sr = self._stacked(engine, plane, pairs)
        est = Krum(1)
        _assert_estimates_equal(est(sr), est(pairs))
        chosen = krum_select(engine.krum_d2(sr), len(pairs), 1)
        assert len(pairs) - 1 not in chosen

    def test_nonfinite_rows_in_coordinate_estimators(self, engine, plane):
        # With the gate off, NaN rows can reach the estimators; numpy
        # sorts NaN last (so the trim may drop it) — the device sort
        # must agree coordinate for coordinate.
        rng = np.random.default_rng(11)
        pairs = [(1.0, _snap(rng)) for _ in range(4)]
        bad = _snap(rng)
        bad["a"] = bad["a"].copy()
        bad["a"][2, 3] = np.inf
        pairs.append((1.0, bad))
        sr = self._stacked(engine, plane, pairs)
        est = TrimmedMean(0.2)
        _assert_estimates_equal(est(sr), est(pairs))

    def test_krum_tiny_cohort_falls_back_to_median(self, engine, plane):
        pairs = _pairs(2, seed=1)
        sr = self._stacked(engine, plane, pairs)
        _assert_estimates_equal(Krum(1)(sr), Krum(1)(pairs))
        _assert_estimates_equal(Krum(1)(sr), Median()(pairs))

    def test_subset_gathers_rows(self, engine, plane):
        pairs = _pairs(5, seed=12)
        sr = self._stacked(engine, plane, pairs)
        sub = sr.subset([0, 2, 4])
        assert len(sub) == 3
        assert sub.weights == [pairs[0][0], pairs[2][0], pairs[4][0]]
        _assert_estimates_equal(
            WeightedMean()(sub),
            weighted_mean([pairs[0], pairs[2], pairs[4]]),
            bitwise_f32=True,
        )

    def test_aggregators_compose_with_stacked_rounds(self, engine, plane):
        pairs = _pairs(5, seed=13)
        sr = self._stacked(engine, plane, pairs)
        rng = np.random.default_rng(14)
        current = _snap(rng)
        for spec, robust in [
            ("fedavg", None), ("fedavgm", None),
            ("fedadam", "median"), ("fedyogi", "trimmed_mean:0.2"),
            ("fedavg", "krum:1"),
        ]:
            a_np = make_aggregator(spec, robust=robust).aggregate(
                pairs, current_global=current
            )
            a_dev = make_aggregator(spec, robust=robust).aggregate(
                sr, current_global=current
            )
            _assert_estimates_equal(
                a_dev, a_np, bitwise_f32=(spec, robust) == ("fedavg", None),
            )


# ---- gate statistic parity ---------------------------------------------------

def _gate(device_engine=None, **kw):
    base = dict(mad_k=4.0, min_cohort=3)
    base.update(kw)
    g = UpdateGate(**base)
    g.set_template(TEMPLATE)
    if device_engine is not None:
        g.set_engine(device_engine)
    return g


def _decisions(result):
    return (
        [c for c, _w, _s in result.accepted],
        [(r.client_id, r.reason) for r in result.rejected],
        [c for c, _n, _m in result.clipped],
    )


class TestGateParity:
    def _cohort(self, seed=21):
        rng = np.random.default_rng(seed)
        glob = _snap(rng)
        cands = []
        for cid in range(5):  # tight cohort around the global
            cands.append(
                (cid, 10.0 + cid, {
                    "a": (glob["a"] + 0.01 * rng.normal(size=(6, 9))
                          ).astype(np.float32),
                    "b": (glob["b"] + 0.01 * rng.normal(size=(17,))
                          ).astype(np.float32),
                    "n": np.int32(2),
                })
            )
        return glob, cands

    def _both(self, engine, cands, glob, round_idx=0, **kw):
        r_np = _gate(**kw).admit_round(
            [(c, w, dict(s)) for c, w, s in cands], glob, round_idx
        )
        r_dev = _gate(device_engine=engine, **kw).admit_round(
            [(c, w, dict(s)) for c, w, s in cands], glob, round_idx
        )
        return r_np, r_dev

    def test_norm_parity(self, engine, plane):
        glob, cands = self._cohort()
        mat = engine.stack(plane, [s for _c, _w, s in cands])
        gvec = engine.put_vector(plane, glob)
        counts, norms = engine.gate_stats(mat, gvec)
        assert not counts.any()
        for i, (_c, _w, s) in enumerate(cands):
            ref = update_norm(s, glob)
            assert abs(norms[i] - ref) <= 1e-6 * max(ref, 1.0)

    def test_clean_cohort_all_admitted(self, engine):
        glob, cands = self._cohort()
        r_np, r_dev = self._both(engine, cands, glob)
        assert _decisions(r_np) == _decisions(r_dev)
        assert len(r_dev.accepted) == 5
        assert r_dev.stacked is not None and len(r_dev.stacked) == 5
        assert r_np.stacked is None  # numpy path never stacks

    def test_mad_outlier_mask_parity(self, engine):
        glob, cands = self._cohort()
        rng = np.random.default_rng(31)
        # One far outlier + one mild straggler: both backends must draw
        # the SAME median+MAD threshold and reject the same set.
        cands.append((90, 1.0, {
            "a": (glob["a"] + 5.0 * rng.normal(size=(6, 9))
                  ).astype(np.float32),
            "b": glob["b"].copy(), "n": np.int32(2),
        }))
        cands.append((91, 1.0, {
            "a": (glob["a"] + 0.05 * rng.normal(size=(6, 9))
                  ).astype(np.float32),
            "b": glob["b"].copy(), "n": np.int32(2),
        }))
        r_np, r_dev = self._both(engine, cands, glob)
        assert _decisions(r_np) == _decisions(r_dev)
        assert (90, "norm_outlier") in _decisions(r_dev)[1]
        # rejection norms agree to 1e-6 relative
        norms_np = {r.client_id: r.norm for r in r_np.rejected}
        norms_dev = {r.client_id: r.norm for r in r_dev.rejected}
        for cid, n_ref in norms_np.items():
            assert abs(norms_dev[cid] - n_ref) <= 1e-6 * max(n_ref, 1.0)

    def test_nonfinite_and_conformance_parity(self, engine):
        glob, cands = self._cohort()
        nan_snap = {k: np.asarray(v).copy() for k, v in cands[1][2].items()}
        nan_snap["b"][3] = np.nan
        cands[1] = (cands[1][0], cands[1][1], nan_snap)
        skew = {k: np.asarray(v) for k, v in cands[2][2].items()}
        skew["a"] = skew["a"][:4]
        cands[2] = (cands[2][0], cands[2][1], skew)
        r_np, r_dev = self._both(engine, cands, glob)
        assert _decisions(r_np) == _decisions(r_dev)
        reasons = dict(_decisions(r_dev)[1])
        assert reasons[1] == "nonfinite" and reasons[2] == "shape_skew"
        # the numpy-style detail (which tensor, how many values) survives
        detail = {r.client_id: r.detail for r in r_dev.rejected}[1]
        assert "b" in detail and "non-finite" in detail

    def test_clip_parity(self, engine):
        glob, cands = self._cohort()
        norms = [update_norm(s, glob) for _c, _w, s in cands]
        cap = float(np.median(norms) * 0.8)  # forces clips, no rejections
        r_np, r_dev = self._both(
            engine, cands, glob, max_update_norm=cap, mad_k=0.0,
        )
        assert _decisions(r_np) == _decisions(r_dev)
        assert r_np.clipped  # the cap actually bit
        # clipped snapshots match the numpy f64 clip to float tolerance,
        # on the host dicts AND through the stacked estimator
        for (c1, _w1, s1), (c2, _w2, s2) in zip(
            r_np.accepted, r_dev.accepted
        ):
            assert c1 == c2
            for k in s1:
                np.testing.assert_allclose(
                    np.asarray(s2[k], np.float64),
                    np.asarray(s1[k], np.float64),
                    rtol=1e-5, atol=1e-6,
                )
        a_np = weighted_mean([(w, s) for _c, w, s in r_np.accepted])
        a_dev = WeightedMean()(r_dev.stacked)
        _assert_estimates_equal(a_dev, a_np)

    def test_f32_norm_overflow_row_matches_oracle(self, engine):
        # Values finite in f32 whose squared sum overflows the f32 plane
        # accumulator (~1e20 coordinates): the device gate recomputes the
        # f64 norm on the host, so the decision AND the recorded norm are
        # the oracle's — rejected via the cohort screen when it is on,
        # CLIPPED AND ADMITTED (not rejected) when only the hard cap is.
        glob, cands = self._cohort()
        big = {
            "a": np.full((6, 9), 1e20, np.float32),
            "b": glob["b"].copy(), "n": np.int32(2),
        }
        cands.append((77, 1.0, big))
        r_np, r_dev = self._both(engine, cands, glob)
        assert _decisions(r_np) == _decisions(r_dev)
        assert (77, "norm_outlier") in _decisions(r_dev)[1]
        n_np = {r.client_id: r.norm for r in r_np.rejected}[77]
        n_dev = {r.client_id: r.norm for r in r_dev.rejected}[77]
        assert np.isfinite(n_dev) and abs(n_dev - n_np) <= 1e-6 * n_np
        r_np2, r_dev2 = self._both(
            engine, cands, glob, mad_k=0.0, max_update_norm=1.0,
        )
        assert _decisions(r_np2) == _decisions(r_dev2)
        assert 77 in [c for c, _n, _m in r_dev2.clipped]
        assert not r_dev2.rejected

    def test_clip_leaves_nonclipped_rows_bitwise(self, engine, plane):
        glob, cands = self._cohort()
        norms = [update_norm(s, glob) for _c, _w, s in cands]
        # midway between the two largest norms: only the max-norm row
        # clips, robustly to the f32-plane norm's ~1e-7 relative noise
        cap = float((sorted(norms)[-2] + sorted(norms)[-1]) / 2.0)
        g = _gate(device_engine=engine, max_update_norm=cap, mad_k=0.0)
        r = g.admit_round([(c, w, dict(s)) for c, w, s in cands], glob, 0)
        clipped_ids = {c for c, _n, _m in r.clipped}
        assert len(clipped_ids) == 1
        rows = np.asarray(r.stacked.mat)[:, :plane.dim]
        for i, (cid, _w, snap) in enumerate(r.accepted):
            if cid in clipped_ids:
                continue
            ref = plane.flatten({k: np.asarray(v) for k, v in snap.items()})
            assert np.array_equal(
                rows[i].view(np.uint32), ref.view(np.uint32)
            ), cid

    def test_check_finite_off_parity(self, engine):
        glob, cands = self._cohort()
        nan_snap = {k: np.asarray(v).copy() for k, v in cands[0][2].items()}
        nan_snap["a"][0, 0] = np.nan
        cands[0] = (cands[0][0], cands[0][1], nan_snap)
        r_np, r_dev = self._both(
            engine, cands, glob, check_finite=False, max_update_norm=1e-3,
        )
        # pre-PR5 semantics: NaN passes, and with check_finite off the
        # norm stage (screen + clip) is disabled on both backends
        assert _decisions(r_np) == _decisions(r_dev)
        assert len(r_dev.accepted) == 5 and not r_dev.clipped

    def test_mad_zero_disables_screen_parity(self, engine):
        glob, cands = self._cohort()
        rng = np.random.default_rng(5)
        cands.append((99, 1.0, {
            "a": (glob["a"] + 100.0 * rng.normal(size=(6, 9))
                  ).astype(np.float32),
            "b": glob["b"].copy(), "n": np.int32(2),
        }))
        r_np, r_dev = self._both(engine, cands, glob, mad_k=0.0)
        assert _decisions(r_np) == _decisions(r_dev)
        assert len(r_dev.accepted) == 6  # outlier admitted: screen off

    def test_streak_accounting_parity(self, engine):
        glob, cands = self._cohort()
        nan_snap = {k: np.asarray(v).copy() for k, v in cands[0][2].items()}
        nan_snap["a"][0, 0] = np.nan
        bad = (cands[0][0], cands[0][1], nan_snap)
        g_np, g_dev = _gate(), _gate(device_engine=engine)
        for r in range(2):
            g_np.admit_round([bad] + cands[1:], glob, r)
            g_dev.admit_round([bad] + cands[1:], glob, r)
            assert g_np.consecutive(0) == g_dev.consecutive(0) == r + 1
        g_np.admit_round(cands, glob, 2)
        g_dev.admit_round(cands, glob, 2)
        assert g_np.consecutive(0) == g_dev.consecutive(0) == 0
        assert g_np.total_rejections == g_dev.total_rejections


# ---- server backend seam -----------------------------------------------------

class TestServerBackendSeam:
    def _server(self, **kw):
        base = dict(min_clients=1, family="avitm",
                    model_kwargs=MODEL_KWARGS,
                    metrics=MetricsLogger(validate=True))
        base.update(kw)
        server = FederatedServer(**base)
        server.template = build_template_model("avitm", 30, MODEL_KWARGS)
        return server

    def _reply(self, client_id, snap, loss=1.0):
        return pb.StepReply(
            client_id=client_id, shared=codec.flatdict_to_bundle(snap),
            loss=loss, nr_samples=4.0,
        )

    def test_invalid_backend_rejected(self):
        with pytest.raises(ValueError):
            self._server(aggregation_backend="gpu")

    def test_auto_resolves_numpy_on_cpu(self):
        server = self._server(aggregation_backend="auto")
        server._ensure_template()
        assert server._agg_backend_resolved == "numpy"
        assert server.update_gate._engine is None

    def test_forced_device_attaches_engine(self):
        server = self._server(aggregation_backend="device")
        server._ensure_template()
        assert server._agg_backend_resolved == "device"
        assert server.update_gate._engine is not None
        assert server.metrics.registry.gauge("agg_backend_device").value == 1.0

    def test_collect_snapshots_returns_stacked_round(self):
        from gfedntm_tpu.federation.registry import ClientRecord

        server = self._server(aggregation_backend="device")
        server.federation.connect_vocab(1, ("a",), 4.0)
        server.federation.connect_ready(1, "localhost:1")
        rec = server.federation.get_clients()[0]
        rec2 = ClientRecord(2, nr_samples=4.0)
        tmpl = server._shared_template()
        out = server._collect_snapshots(
            [(rec, self._reply(1, tmpl)), (rec2, self._reply(2, tmpl))],
            iteration=0,
        )
        assert isinstance(out, StackedRound) and len(out) == 2
        avg = server.aggregator.aggregate(
            out, current_global=server._current_global()
        )
        ref = weighted_mean([(4.0, tmpl), (4.0, tmpl)])
        _assert_estimates_equal(avg, ref, bitwise_f32=True)

    def test_device_poisoned_admission_matches_numpy(self):
        """The TestServerAdmission NaN→probation→drop ladder, on the
        device backend: identical per-round decisions and counters."""
        from gfedntm_tpu.federation.registry import ClientRecord

        server = self._server(
            aggregation_backend="device", probation_rounds=2,
        )
        server.federation.connect_vocab(1, ("a",), 4.0)
        server.federation.connect_ready(1, "localhost:1")
        rec = server.federation.get_clients()[0]
        tmpl = server._shared_template()
        poisoned = {
            k: np.full_like(v, np.nan) if v.dtype.kind == "f" else v
            for k, v in tmpl.items()
        }
        good = ClientRecord(2, nr_samples=4.0)
        for it, (status_after, streak) in enumerate(
            [("active", 1), (SUSPECT, 2), (DROPPED, 3)]
        ):
            out = server._collect_snapshots(
                [(rec, self._reply(1, poisoned)),
                 (good, self._reply(2, tmpl))], iteration=it,
            )
            assert len(out) == 1
            assert rec.status == status_after
        assert server.metrics.registry.counter(
            "updates_rejected"
        ).value == 3


# ---- CLI ---------------------------------------------------------------------

def test_parser_agg_backend_flag():
    p = build_parser()
    assert p.parse_args([]).agg_backend == "auto"
    assert p.parse_args(
        ["--agg_backend", "device"]
    ).agg_backend == "device"
    with pytest.raises(SystemExit):
        p.parse_args(["--agg_backend", "gpu"])


# ---- e2e federations: device backend vs numpy backend ------------------------

def _import_federation_helpers():
    # Shared chaos harness from the PR 5 suite (same directory, imported
    # under pytest's prepend import mode).
    from test_data_plane import _corpora, _run_federation

    return _corpora, _run_federation


def test_e2e_device_backend_matches_numpy_betas(tmp_path):
    """ISSUE 6 acceptance: a 4-client federation on the device backend
    produces the same betas as the numpy backend — FedAvg's weighted mean
    is bitwise on the plane, so the runs should track each other to float
    noise from the clients' own training."""
    _corpora, _run_federation = _import_federation_helpers()
    corpora = _corpora(4, docs=16, seed=7)
    kwargs = dict(MODEL_KWARGS, num_epochs=1)
    server_np, _ = _run_federation(
        tmp_path, corpora, "e2e-numpy",
        model_kwargs=kwargs, aggregation_backend="numpy",
    )
    server_dev, _ = _run_federation(
        tmp_path, corpora, "e2e-device",
        model_kwargs=kwargs, aggregation_backend="device",
    )
    assert server_np.global_betas is not None
    assert server_dev.global_betas is not None
    assert np.isfinite(server_dev.global_betas).all()
    np.testing.assert_allclose(
        server_dev.global_betas, server_np.global_betas,
        rtol=1e-4, atol=1e-5,
    )


@pytest.mark.chaos
def test_poisoned_client_chaos_on_device_backend(tmp_path):
    """The PR 5 poisoned-client chaos scenario re-run with
    backend="device": client 4 emits 100x-scaled updates, the device
    gate rejects them (norm_outlier), the poisoned client lands in
    probation with reason="poisoned", and the final model matches the
    honest 3-client baseline run on the NUMPY backend — the chaos
    guarantee carries across the backend seam."""
    _corpora, _run_federation = _import_federation_helpers()
    corpora = _corpora(4, docs=24, seed=5)
    baseline_server, _ = _run_federation(
        tmp_path, corpora[:3], "dev-base",
        robust_aggregator="trimmed_mean:0.25", outlier_mad_k=6.0,
        aggregation_backend="numpy",
    )
    base_betas = baseline_server.global_betas
    assert base_betas is not None and np.isfinite(base_betas).all()

    metrics = MetricsLogger(validate=True)
    server, clients = _run_federation(
        tmp_path, corpora, "dev-poison", metrics=metrics,
        poisoned_peer="client4", payload="scale:100",
        robust_aggregator="trimmed_mean:0.25", outlier_mad_k=6.0,
        aggregation_backend="device",
    )
    assert server._agg_backend_resolved == "device"
    assert server.global_betas is not None
    np.testing.assert_allclose(
        server.global_betas, base_betas, rtol=1e-4, atol=1e-5,
    )
    rejections = metrics.events("update_rejected")
    assert rejections and all(
        e["client"] == 4 and e["reason"] == "norm_outlier"
        for e in rejections
    )
    rec = {r.client_id: r for r in server.federation.get_clients()}[4]
    assert rec.status in (SUSPECT, DROPPED)
    assert rec.suspect_reason == "poisoned"
    for c in clients[:3]:
        assert c.stepper.finished
