"""End-to-end numerical parity: Flax AVITM network vs a torch reference model.

The torch model below is written from the architecture spec
(``decoder_network.py:10-135``, ``inference_network.py:7-85``): ProdLDA with
softplus MLP encoder, affine-free BatchNorm heads, learnable priors, xavier
beta. With identical weights, dropout=0 and reparameterization noise eps=0,
forward outputs, ELBO loss, gradients, and one Adam(betas=(0.99, 0.99)) step
must match to float32 tolerance.
"""

from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import optax
import torch
from torch import nn
from torch.nn import functional as F

from gfedntm_tpu.models.losses import avitm_loss
from gfedntm_tpu.models.networks import DecoderNetwork

V, K, H = 40, 6, (17, 13)


class TorchAvitm(nn.Module):
    def __init__(self):
        super().__init__()
        self.input_layer = nn.Linear(V, H[0])
        self.hidden1 = nn.Linear(H[0], H[1])
        self.f_mu = nn.Linear(H[1], K)
        self.f_mu_bn = nn.BatchNorm1d(K, affine=False)
        self.f_sigma = nn.Linear(H[1], K)
        self.f_sigma_bn = nn.BatchNorm1d(K, affine=False)
        self.prior_mean = nn.Parameter(torch.zeros(K))
        self.prior_variance = nn.Parameter(torch.full((K,), 1.0 - 1.0 / K))
        self.beta = nn.Parameter(torch.empty(K, V))
        nn.init.xavier_uniform_(self.beta)
        self.beta_bn = nn.BatchNorm1d(V, affine=False)

    def forward(self, x):
        h = F.softplus(self.input_layer(x))
        h = F.softplus(self.hidden1(h))
        mu = self.f_mu_bn(self.f_mu(h))
        log_sigma = self.f_sigma_bn(self.f_sigma(h))
        theta = F.softmax(mu, dim=1)  # eps = 0 -> z = mu
        word_dist = F.softmax(self.beta_bn(torch.matmul(theta, self.beta)), dim=1)
        return mu, log_sigma, word_dist

    def loss(self, x, mu, log_sigma, word_dist):
        var = torch.exp(log_sigma)
        var_division = torch.sum(var / self.prior_variance, dim=1)
        diff = self.prior_mean - mu
        diff_term = torch.sum(diff * diff / self.prior_variance, dim=1)
        logvar_det = self.prior_variance.log().sum() - log_sigma.sum(dim=1)
        KL = 0.5 * (var_division + diff_term - K + logvar_det)
        RL = -torch.sum(x * torch.log(word_dist + 1e-10), dim=1)
        return (KL + RL).sum()


def flax_variables_from_torch(tm: TorchAvitm):
    def w(layer):
        return layer.weight.detach().numpy().T

    def b(layer):
        return layer.bias.detach().numpy()

    params = {
        "prior_mean": tm.prior_mean.detach().numpy(),
        "prior_variance": tm.prior_variance.detach().numpy(),
        "beta": tm.beta.detach().numpy(),
        "inf_net": {
            "input_layer": {"kernel": w(tm.input_layer), "bias": b(tm.input_layer)},
            "hiddens_l0": {"kernel": w(tm.hidden1), "bias": b(tm.hidden1)},
            "f_mu": {"kernel": w(tm.f_mu), "bias": b(tm.f_mu)},
            "f_sigma": {"kernel": w(tm.f_sigma), "bias": b(tm.f_sigma)},
        },
    }
    zero_bn = lambda n: {  # noqa: E731
        "running_mean": np.zeros(n, np.float32),
        "running_var": np.ones(n, np.float32),
        "num_batches_tracked": np.zeros((), np.int32),
    }
    batch_stats = {
        "beta_batchnorm": zero_bn(V),
        "inf_net": {"f_mu_batchnorm": zero_bn(K), "f_sigma_batchnorm": zero_bn(K)},
    }
    # jnp.asarray can alias numpy buffers zero-copy on CPU, and the torch
    # optimizer mutates its params in place — copy so the trees are disjoint.
    return {
        "params": jax.tree.map(lambda a: jnp.array(np.array(a, copy=True)), params),
        "batch_stats": jax.tree.map(
            lambda a: jnp.array(np.array(a, copy=True)), batch_stats
        ),
    }


def make_models():
    torch.manual_seed(0)
    tm = TorchAvitm()
    fm = DecoderNetwork(
        input_size=V, n_components=K, model_type="prodLDA",
        hidden_sizes=H, activation="softplus", dropout=0.0,
    )
    variables = flax_variables_from_torch(tm)
    return tm, fm, variables


def test_forward_and_loss_parity(rng):
    tm, fm, variables = make_models()
    x = rng.integers(0, 4, size=(12, V)).astype(np.float32)

    tm.train()
    mu_t, ls_t, wd_t = tm(torch.from_numpy(x))
    loss_t = tm.loss(torch.from_numpy(x), mu_t, ls_t, wd_t)

    out, _ = fm.apply(
        variables, jnp.asarray(x), train=True,
        noise=jnp.zeros((12, K)), mutable=["batch_stats"],
        rngs={"dropout": jax.random.PRNGKey(0)},
    )
    loss_f = avitm_loss(
        jnp.asarray(x), out.word_dist, out.prior_mean, out.prior_variance,
        out.posterior_mean, out.posterior_variance, out.posterior_log_variance,
    )

    np.testing.assert_allclose(np.asarray(out.posterior_mean), mu_t.detach().numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.word_dist), wd_t.detach().numpy(), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(float(loss_f), float(loss_t), rtol=1e-4)


@pytest.mark.slow
def test_adam_step_parity(rng):
    tm, fm, variables = make_models()
    x = rng.integers(0, 4, size=(12, V)).astype(np.float32)

    # --- torch step
    tm.train()
    opt_t = torch.optim.Adam(tm.parameters(), lr=2e-3, betas=(0.99, 0.99))
    opt_t.zero_grad()
    mu_t, ls_t, wd_t = tm(torch.from_numpy(x))
    loss_t = tm.loss(torch.from_numpy(x), mu_t, ls_t, wd_t)
    loss_t.backward()
    opt_t.step()

    # --- flax step
    def loss_fn(params):
        out, mut = fm.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            jnp.asarray(x), train=True, noise=jnp.zeros((12, K)),
            mutable=["batch_stats"], rngs={"dropout": jax.random.PRNGKey(0)},
        )
        return avitm_loss(
            jnp.asarray(x), out.word_dist, out.prior_mean, out.prior_variance,
            out.posterior_mean, out.posterior_variance, out.posterior_log_variance,
        ), mut

    (loss_f, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(variables["params"])
    tx = optax.adam(2e-3, b1=0.99, b2=0.99, eps=1e-8)
    opt_state = tx.init(variables["params"])
    updates, _ = tx.update(grads, opt_state, variables["params"])
    new_params = optax.apply_updates(variables["params"], updates)

    np.testing.assert_allclose(float(loss_f), float(loss_t), rtol=1e-4)

    # Gradient parity (scaled atol: BN makes e.g. grad(prior_mean) exactly
    # cancel in math, so only noise remains there — compare with atol tied to
    # the overall gradient scale, not elementwise rtol).
    grad_pairs = OrderedDict(
        beta=(grads["beta"], tm.beta.grad),
        prior_mean=(grads["prior_mean"], tm.prior_mean.grad),
        prior_variance=(grads["prior_variance"], tm.prior_variance.grad),
        input_kernel=(grads["inf_net"]["input_layer"]["kernel"],
                      tm.input_layer.weight.grad.T),
        f_mu_kernel=(grads["inf_net"]["f_mu"]["kernel"], tm.f_mu.weight.grad.T),
    )
    # grad(f_mu.bias) cancels exactly through BN centering — both sides must
    # be numerically tiny, but their noise is uncorrelated.
    assert np.abs(np.asarray(grads["inf_net"]["f_mu"]["bias"])).max() < 5e-3
    assert np.abs(tm.f_mu.bias.grad.numpy()).max() < 5e-3
    for name, (f_leaf, t_leaf) in grad_pairs.items():
        t_np = t_leaf.detach().numpy()
        scale = max(np.abs(t_np).max(), 1.0)
        np.testing.assert_allclose(
            np.asarray(f_leaf), t_np, rtol=1e-3, atol=1e-4 * scale, err_msg=name
        )

    # Post-Adam parameter parity for well-conditioned leaves (a single Adam
    # step turns near-zero gradients into +-lr noise, so degenerate leaves
    # like prior_mean are covered by the gradient check above instead).
    param_pairs = OrderedDict(
        beta=(new_params["beta"], tm.beta),
        prior_variance=(new_params["prior_variance"], tm.prior_variance),
        input_kernel=(new_params["inf_net"]["input_layer"]["kernel"],
                      tm.input_layer.weight.detach().T),
        f_mu_kernel=(new_params["inf_net"]["f_mu"]["kernel"],
                     tm.f_mu.weight.detach().T),
    )
    for name, (f_leaf, t_leaf) in param_pairs.items():
        np.testing.assert_allclose(
            np.asarray(f_leaf), t_leaf.detach().numpy(), rtol=2e-3, atol=1e-5,
            err_msg=name,
        )
