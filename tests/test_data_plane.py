"""Data-plane hardening suite (tier-1): update admission gate,
byzantine-robust aggregation, divergence detection + checkpoint rollback,
payload-corruption fault injection, and checkpoint integrity.

The `chaos` tests run real gRPC federations in-process where one client is
scripted (via the FaultInjector's payload faults) to emit NaN / 100x-scaled
updates — the acceptance scenarios of ISSUE 5: robust aggregation matches
the honest-clients-only baseline while the poisoned client lands in
probation, and a scripted divergence triggers exactly one rollback to the
last good checkpointed round before training resumes to completion.
"""

import json
import threading

import numpy as np
import pytest

from gfedntm_tpu.cli import build_parser
from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.federation import codec
from gfedntm_tpu.federation.aggregation import (
    FedAdam,
    FedAvg,
    Krum,
    Median,
    TrimmedMean,
    make_aggregator,
    make_estimator,
    weighted_mean,
)
from gfedntm_tpu.federation.client import Client
from gfedntm_tpu.federation.compression import (
    DownlinkEncoder,
    ReferenceMismatch,
    UplinkDecoder,
    UplinkEncoder,
    WireCodec,
)
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.federation.registry import DROPPED, SUSPECT, Federation
from gfedntm_tpu.federation.resilience import FaultInjector, corrupt_bundle
from gfedntm_tpu.federation.sanitize import UpdateGate, update_norm
from gfedntm_tpu.federation.server import FederatedServer, build_template_model
from gfedntm_tpu.train.checkpoint import (
    CheckpointIntegrityError,
    FederationCheckpointer,
)
from gfedntm_tpu.train.guardian import DivergenceGuardian
from gfedntm_tpu.utils.observability import MetricsLogger

MODEL_KWARGS = dict(
    n_components=3, hidden_sizes=(8,), batch_size=8, num_epochs=2, seed=0,
)


def _snaps(*vecs, weight=1.0):
    return [(weight, {"x": np.asarray(v, np.float32)}) for v in vecs]


# ---- robust estimators ------------------------------------------------------

class TestEstimators:
    honest = ([1.0, 2.0], [1.1, 2.1], [0.9, 1.9])

    def test_median_ignores_scaled_attacker(self):
        est = Median()(_snaps(*self.honest, [100.0, 200.0]))
        np.testing.assert_allclose(est["x"], [1.05, 2.05], rtol=1e-5)

    def test_trimmed_mean_drops_extremes(self):
        est = TrimmedMean(0.25)(_snaps(*self.honest, [100.0, 200.0]))
        np.testing.assert_allclose(est["x"], [1.05, 2.05], rtol=1e-5)
        # frac too large for the cohort degrades gracefully to the median
        est = TrimmedMean(0.49)(_snaps(*self.honest))
        np.testing.assert_allclose(est["x"], [1.0, 2.0], rtol=1e-5)
        with pytest.raises(ValueError):
            TrimmedMean(0.5)

    def test_krum_selects_honest_cluster(self):
        est = Krum(1)(_snaps(*self.honest, [100.0, 200.0]))
        np.testing.assert_allclose(est["x"], [1.0, 2.0], rtol=1e-5)

    def test_krum_never_selects_nonfinite(self):
        est = Krum(1)(_snaps(*self.honest, [np.nan, np.nan]))
        assert np.isfinite(est["x"]).all()
        np.testing.assert_allclose(est["x"], [1.0, 2.0], rtol=1e-5)

    def test_krum_tiny_cohort_falls_back_to_median(self):
        est = Krum(2)(_snaps([1.0, 2.0], [3.0, 4.0]))
        np.testing.assert_allclose(est["x"], [2.0, 3.0])

    def test_estimators_keep_dtype(self):
        out = Median()(_snaps(*self.honest))
        assert out["x"].dtype == np.float32

    def test_make_estimator_specs(self):
        assert make_estimator(None).name == "mean"
        assert make_estimator("median").name == "median"
        assert make_estimator("trimmed_mean:0.25").name == "trimmed_mean:0.25"
        assert make_estimator("krum:2").f == 2
        with pytest.raises(ValueError):
            make_estimator("geometric_median")
        with pytest.raises(ValueError):
            make_estimator("median:0.5")

    def test_aggregator_composition_and_names(self):
        assert make_aggregator("fedavg").name == "fedavg"  # unchanged
        agg = make_aggregator("fedadam", robust="median")
        assert agg.name == "fedadam+median"
        assert make_aggregator("median").name == "fedavg+median"
        assert make_aggregator("krum:1").name == "fedavg+krum:1"
        with pytest.raises(ValueError):
            make_aggregator("median", robust="krum:1")
        with pytest.raises(ValueError):
            make_aggregator("blah")
        # a bare robust spec has no server optimizer: reject its kwargs
        # cleanly instead of a TypeError deep in FedAvg.__init__
        with pytest.raises(ValueError, match="server-optimizer"):
            make_aggregator("median", server_lr=0.5)

    def test_robust_estimate_feeds_server_optimizer(self):
        """A composed fedadam+median must move toward the MEDIAN, not the
        attacker-dragged mean."""
        current = {"x": np.zeros(2, np.float32)}
        snaps = _snaps(*self.honest, [1000.0, 2000.0])
        plain = FedAdam(server_lr=0.5).aggregate(snaps, current)
        robust = FedAdam(server_lr=0.5, estimator="median").aggregate(
            snaps, current
        )
        mean_est = weighted_mean(snaps)["x"]
        # same update rule, different estimate: the robust pseudo-gradient
        # is bounded by the honest cluster
        assert np.all(np.abs(robust["x"]) < np.abs(mean_est))
        assert plain is not robust

    def test_fedavg_with_estimator_assigns_estimate(self):
        snaps = _snaps(*self.honest, [100.0, 200.0])
        out = FedAvg(estimator="trimmed_mean:0.25").aggregate(snaps)
        np.testing.assert_allclose(out["x"], [1.05, 2.05], rtol=1e-5)


# ---- update admission gate --------------------------------------------------

def _gate(**kw):
    kw.setdefault("metrics", MetricsLogger(validate=True))
    gate = UpdateGate(**kw)
    gate.set_template({"a": np.zeros((2,), np.float32),
                       "b": np.zeros((3,), np.float32)})
    return gate


def _cand(client_id, a=(0.1, 0.1), b=(0.1, 0.1, 0.1), weight=1.0):
    return (client_id, weight,
            {"a": np.asarray(a, np.float32), "b": np.asarray(b, np.float32)})


REF = {"a": np.zeros((2,), np.float32), "b": np.zeros((3,), np.float32)}


class TestUpdateGate:
    def test_conformance_rejections(self):
        gate = _gate()
        bad_keys = (1, 1.0, {"a": np.zeros(2, np.float32)})
        bad_shape = (2, 1.0, {"a": np.zeros(5, np.float32),
                              "b": np.zeros(3, np.float32)})
        bad_dtype = (3, 1.0, {"a": np.zeros(2, np.float64),
                              "b": np.zeros(3, np.float32)})
        res = gate.admit_round(
            [_cand(4), bad_keys, bad_shape, bad_dtype], REF, round_idx=0
        )
        assert [c for c, _w, _s in res.accepted] == [4]
        reasons = {r.client_id: r.reason for r in res.rejected}
        assert reasons == {1: "key_skew", 2: "shape_skew", 3: "dtype_skew"}
        reg = gate.metrics.registry
        assert reg.counter("updates_rejected").value == 3
        # dashboard continuity with the PR 2 conformance counter
        assert reg.counter("key_skew_excluded").value == 3
        events = gate.metrics.events("update_rejected")
        assert len(events) == 3 and all("reason" in e for e in events)

    def test_nonfinite_rejected_with_detail(self):
        gate = _gate()
        nan = _cand(7, a=(np.nan, 0.0))
        res = gate.admit_round([_cand(1), nan], REF, round_idx=3)
        assert [r.client_id for r in res.rejected] == [7]
        assert res.rejected[0].reason == "nonfinite"
        assert "a" in res.rejected[0].detail

    def test_nonfinite_passes_when_disabled(self):
        gate = _gate(check_finite=False, mad_k=0.0)
        res = gate.admit_round([_cand(1, a=(np.nan, 0.0))], REF, 0)
        assert len(res.accepted) == 1 and not res.rejected

    def test_norm_outlier_needs_cohort(self):
        gate = _gate(mad_k=4.0)
        huge = _cand(9, a=(1e4, 1e4), b=(1e4, 1e4, 1e4))
        # cohort of 2: MAD is meaningless, nothing rejected
        res = gate.admit_round([_cand(1), huge], REF, 0)
        assert not res.rejected
        # cohort of 4: the outlier goes
        res = gate.admit_round(
            [_cand(1), _cand(2), _cand(3), huge], REF, 1
        )
        assert [r.client_id for r in res.rejected] == [9]
        assert res.rejected[0].reason == "norm_outlier"
        assert res.rejected[0].norm > 1e4

    def test_mad_zero_disables_outlier_screen(self):
        gate = _gate(mad_k=0.0)
        huge = _cand(9, a=(1e4, 1e4))
        res = gate.admit_round(
            [_cand(1), _cand(2), _cand(3), huge], REF, 0
        )
        assert not res.rejected

    def test_hard_clip_bounds_influence(self):
        gate = _gate(mad_k=0.0, max_update_norm=0.5)
        big = _cand(5, a=(3.0, 4.0), b=(0.0, 0.0, 0.0))  # norm 5
        res = gate.admit_round([big], REF, 0)
        assert len(res.accepted) == 1 and not res.rejected
        assert res.clipped == [(5, pytest.approx(5.0), 0.5)]
        _cid, _w, snap = res.accepted[0]
        assert update_norm(snap, REF) == pytest.approx(0.5, rel=1e-6)
        # direction preserved
        np.testing.assert_allclose(
            snap["a"] / np.linalg.norm(snap["a"]), [0.6, 0.8], rtol=1e-5
        )
        assert gate.metrics.registry.counter("updates_clipped").value == 1
        assert gate.metrics.events("update_clipped")[0]["client"] == 5

    def test_consecutive_streak_resets_on_acceptance(self):
        gate = _gate()
        nan = _cand(7, a=(np.nan, 0.0))
        gate.admit_round([nan], REF, 0)
        gate.admit_round([nan], REF, 1)
        assert gate.consecutive(7) == 2
        assert gate.total_rejections[7] == 2
        gate.admit_round([_cand(7)], REF, 2)
        assert gate.consecutive(7) == 0
        assert gate.total_rejections[7] == 2  # totals never reset

    def test_knob_validation(self):
        with pytest.raises(ValueError):
            UpdateGate(max_update_norm=0.0)
        with pytest.raises(ValueError):
            UpdateGate(suspect_after=0)


# ---- divergence guardian ----------------------------------------------------

class TestGuardian:
    def test_nonfinite_global_is_immediate(self):
        g = DivergenceGuardian(patience=5)
        avg = {"x": np.array([1.0, np.nan], np.float32)}
        assert g.observe(0, [1.0], avg) == "nonfinite_global"
        assert not g.healthy

    def test_loss_explosion_respects_patience(self):
        g = DivergenceGuardian(patience=2, loss_factor=4.0)
        avg = {"x": np.ones(2, np.float32)}
        for r in range(3):
            assert g.observe(r, [100.0], avg) is None
        assert g.healthy
        assert g.observe(3, [1e5], avg, [(1, 1.0)]) is None  # streak 1
        assert not g.healthy
        assert g.observe(4, [1e5], avg, [(1, 1.0)]) == "loss_explosion"

    def test_healthy_round_resets_streak(self):
        g = DivergenceGuardian(patience=2, loss_factor=4.0)
        avg = {"x": np.ones(2, np.float32)}
        g.observe(0, [100.0], avg)
        assert g.observe(1, [1e5], avg) is None
        assert g.observe(2, [100.0], avg) is None  # recovered on its own
        assert g.healthy
        assert g.observe(3, [1e5], avg) is None  # streak restarts at 1

    def test_bad_rounds_do_not_drag_the_baseline(self):
        g = DivergenceGuardian(patience=3, loss_factor=4.0)
        avg = {"x": np.ones(2, np.float32)}
        g.observe(0, [100.0], avg)
        g.observe(1, [1e5], avg)
        g.observe(2, [1e5], avg)
        # EWMA still anchored at ~100: the third bad round trips
        assert g.observe(3, [1e5], avg) == "loss_explosion"

    def test_norm_explosion(self):
        g = DivergenceGuardian(patience=1, norm_factor=10.0)
        small = {"x": np.ones(4, np.float32)}
        assert g.observe(0, [1.0], small) is None
        assert g.observe(1, [1.0], {"x": np.full(4, 1e3, np.float32)}) \
            == "norm_explosion"

    def test_dominant_contributors(self):
        g = DivergenceGuardian(patience=2, loss_factor=4.0,
                               dominance_factor=2.0)
        avg = {"x": np.ones(2, np.float32)}
        g.observe(0, [1.0], avg)
        g.observe(1, [1e9], avg, [(1, 10.0), (2, 1.0), (3, 1.0)])
        assert g.dominant_contributors() == [1]
        g.note_rollback()
        assert g.healthy and g.dominant_contributors() == []

    def test_single_byzantine_loss_report_cannot_force_rollback(self):
        """StepReply.loss is attacker-controlled: one admitted client
        reporting NaN / 1e30 losses forever must never trip a divergence
        (the round statistic is a median over finite reports)."""
        g = DivergenceGuardian(patience=1, loss_factor=4.0)
        avg = {"x": np.ones(2, np.float32)}
        for r in range(6):
            lie = np.nan if r % 2 else 1e30
            assert g.observe(r, [100.0, 101.0, 99.0, lie], avg) is None
            assert g.healthy
        # ... but a cohort-wide non-finite report is a real signal
        assert g.observe(9, [np.nan, np.nan, np.nan], avg) \
            == "loss_explosion"

    def test_validation(self):
        with pytest.raises(ValueError):
            DivergenceGuardian(patience=0)
        with pytest.raises(ValueError):
            DivergenceGuardian(loss_factor=1.0)


# ---- payload-corruption faults ----------------------------------------------

def _bundle(values):
    return codec.flatdict_to_bundle(
        {"x": np.asarray(values, np.float32),
         "n": np.array([3], np.int32)}
    )


class TestCorruptFaults:
    def test_corrupt_bundle_modes(self):
        b = _bundle([1.0, 2.0, 3.0])
        corrupt_bundle(b, "nan")
        out = codec.bundle_to_flatdict(b)
        assert np.isnan(out["x"]).all()
        assert out["n"].tolist() == [3]  # integer records untouched

        b = _bundle([1.0, 2.0, 3.0])
        corrupt_bundle(b, "scale:100")
        np.testing.assert_allclose(
            codec.bundle_to_flatdict(b)["x"], [100.0, 200.0, 300.0]
        )

        b = _bundle([1.0, 2.0, 3.0])
        corrupt_bundle(b, "random", seed=7)
        r1 = codec.bundle_to_flatdict(b)["x"]
        b2 = _bundle([1.0, 2.0, 3.0])
        corrupt_bundle(b2, "random", seed=7)
        np.testing.assert_array_equal(r1, codec.bundle_to_flatdict(b2)["x"])
        assert not np.allclose(r1, [1.0, 2.0, 3.0])

    def test_invalid_corrupt_spec_rejected(self):
        inj = FaultInjector(seed=0)
        with pytest.raises(ValueError):
            inj.script("TrainStep", kind="corrupt", payload="explode")
        with pytest.raises(ValueError):
            inj.script("TrainStep", kind="corrupt")

    def test_after_call_corrupts_matching_reply_with_skip(self):
        inj = FaultInjector(seed=0)
        inj.script("TrainStep", kind="corrupt", payload="nan", times=1,
                   peer="client1", skip=2)
        for i in range(2):  # skip window: untouched
            reply = pb.StepReply(client_id=1, shared=_bundle([1.0, 2.0]))
            inj.after_call("svc", "TrainStep", reply, peer="client1")
            assert np.isfinite(
                codec.bundle_to_flatdict(reply.shared)["x"]
            ).all()
        assert inj.fired == []
        # wrong peer / wrong direction: untouched
        other = pb.StepReply(client_id=2, shared=_bundle([1.0, 2.0]))
        inj.after_call("svc", "TrainStep", other, peer="client2")
        inj.before_call("svc", "TrainStep", peer="client1")  # no raise
        assert np.isfinite(codec.bundle_to_flatdict(other.shared)["x"]).all()
        # armed now
        reply = pb.StepReply(client_id=1, shared=_bundle([1.0, 2.0]))
        inj.after_call("svc", "TrainStep", reply, peer="client1")
        assert np.isnan(codec.bundle_to_flatdict(reply.shared)["x"]).all()
        assert inj.fired == [("TrainStep", "client1", "corrupt")]
        assert inj.pending() == 0

    def test_corrupt_composes_with_wire_codec(self):
        """Scaling the WIRE values of a delta+fp16 uplink must decode to a
        correspondingly poisoned snapshot server-side."""
        wc = WireCodec("delta+fp16")
        enc = UplinkEncoder(wc)
        dec = UplinkDecoder(wc)
        ref = {"x": np.ones(4, np.float32)}
        enc.note_aggregate(ref, 0)
        dec.note_push(0, ref)
        bundle = enc.encode({"x": ref["x"] + 0.25})
        corrupt_bundle(bundle, "scale:100")
        out = dec.decode(bundle)
        np.testing.assert_allclose(out["x"], 1.0 + 25.0, rtol=1e-2)


# ---- wire-codec session reset (rollback support) ----------------------------

class TestCodecReset:
    def test_downlink_encoder_reset_forces_self_contained(self):
        m = MetricsLogger(validate=True)
        enc = DownlinkEncoder(WireCodec("delta"), metrics=m)
        avg = {"x": np.ones(3, np.float32)}
        enc.encode(avg, round_idx=0)
        bundle, _view = enc.encode(avg, round_idx=1, allow_delta=True)
        assert bundle.ref_round == 1  # deltaed against round 0
        enc.reset()
        bundle, _view = enc.encode(avg, round_idx=2, allow_delta=True)
        assert bundle.ref_round == 0  # self-contained despite allow_delta
        assert m.registry.counter("codec_resets").value == 1

    def test_uplink_decoder_reset_drops_reference_cache(self):
        wc = WireCodec("delta")
        enc = UplinkEncoder(wc)
        dec = UplinkDecoder(wc)
        ref = {"x": np.ones(3, np.float32)}
        enc.note_aggregate(ref, 0)
        dec.note_push(0, ref)
        bundle = enc.encode({"x": ref["x"] + 1.0})
        assert dec.decode(bundle)  # decodes fine with the cached ref
        dec.reset()
        with pytest.raises(ReferenceMismatch):
            dec.decode(enc.encode({"x": ref["x"] + 2.0}))

    def test_reset_clears_error_feedback_residual_and_ref(self):
        enc = UplinkEncoder(WireCodec("delta+topk:0.5"))
        enc.note_aggregate({"x": np.zeros(4, np.float32)}, 0)
        enc.encode({"x": np.array([1.0, 0.1, 0.2, 3.0], np.float32)})
        assert any(np.any(v) for v in enc.residual.values())
        enc.reset()
        assert enc.residual == {}
        # the applied-aggregate reference is gone too: the next snapshot
        # encodes self-contained, carrying no diverged-trajectory mass
        bundle = enc.encode({"x": np.ones(4, np.float32)})
        assert bundle.ref_round == 0

    def test_reset_session_flag_resets_client_sessions(self):
        """An Aggregate carrying reset_session must drop the client's
        delta refs AND error-feedback residual before applying (the
        divergence-rollback re-broadcast contract)."""
        from gfedntm_tpu.federation.client import FederatedClientServicer
        from gfedntm_tpu.federation.compression import DownlinkDecoder

        class _Stepper:
            current_mb = 1
            current_epoch = 0
            finished = False

            def delta_update_fit(self, averaged):
                import types
                self.applied = averaged
                return types.SimpleNamespace(
                    epoch_ended=False, finished=False, current_epoch=0,
                    epoch_loss=None,
                )

        wc = WireCodec("delta+topk:0.5")
        uplink = UplinkEncoder(wc)
        downlink = DownlinkDecoder(wc)
        # seed session state as if rounds already ran
        ref = {"x": np.ones(4, np.float32)}
        uplink.note_aggregate(ref, 3)
        downlink._ref, downlink._ref_round = dict(ref), 3
        uplink.encode({"x": np.array([2.0, 1.0, 1.1, 1.2], np.float32)})
        assert uplink.residual and uplink._ref is not None

        import logging
        servicer = FederatedClientServicer(
            1, _Stepper(), on_stop=lambda: None,
            logger=logging.getLogger("t"), uplink=uplink, downlink=downlink,
        )
        enc = DownlinkEncoder(wc)
        bundle, _view = enc.encode({"x": np.full(4, 5.0, np.float32)},
                                   round_idx=7)
        servicer.ApplyAggregate(
            pb.Aggregate(shared=bundle, round=7, reset_session=True), None
        )
        assert uplink.residual == {} or not any(
            np.any(v) for v in uplink.residual.values()
        )
        # the uplink ref is the freshly applied push, not the old round-3
        # state; the downlink ref was rebuilt from the reset too
        assert uplink._ref_round == 7 and downlink._ref_round == 7
        np.testing.assert_allclose(uplink._ref["x"], 5.0, rtol=1e-2)


# ---- checkpoint integrity ---------------------------------------------------

class TestCheckpointIntegrity:
    def _saved(self, tmp_path):
        ckpt = FederationCheckpointer(str(tmp_path))
        ckpt.save_round(4, {"a": np.ones(2, np.float32)}, [], vocab=["x"])
        return ckpt

    def test_corrupt_sidecar_fails_actionably(self, tmp_path):
        ckpt = self._saved(tmp_path)
        with open(ckpt.meta_path, "w") as fh:
            fh.write('{"round": 4, "average_keys": ["a"')  # truncated
        with pytest.raises(CheckpointIntegrityError, match="truncated"):
            ckpt.load_meta()
        ckpt.close()

    def test_missing_required_keys_fail(self, tmp_path):
        ckpt = self._saved(tmp_path)
        with open(ckpt.meta_path, "w") as fh:
            json.dump({"vocab": ["x"]}, fh)
        with pytest.raises(CheckpointIntegrityError, match="average_keys"):
            ckpt.load_meta()
        ckpt.close()

    def test_round_mismatch_with_no_matching_round_fails(self, tmp_path):
        ckpt = self._saved(tmp_path)
        meta = ckpt.load_meta()
        meta["round"] = 2  # a round that never existed on disk
        with open(ckpt.meta_path, "w") as fh:
            json.dump(meta, fh)
        with pytest.raises(CheckpointIntegrityError, match="mismatch"):
            ckpt.restore_round({"a": np.zeros(2, np.float32)})
        ckpt.close()

    def test_stale_sidecar_falls_back_to_its_own_round(self, tmp_path):
        """The benign crash window — orbax wrote round 6, the crash landed
        before the sidecar rewrite, so the sidecar still describes round
        4: resume must come back from round 4 (whose halves agree), not
        fail demanding manual surgery."""
        ckpt = FederationCheckpointer(str(tmp_path))
        ckpt.save_round(4, {"a": np.full(2, 4.0, np.float32)}, [],
                        vocab=["x"])
        stale = open(ckpt.meta_path).read()
        ckpt.save_round(6, {"a": np.full(2, 6.0, np.float32)}, [],
                        vocab=["x"])
        with open(ckpt.meta_path, "w") as fh:
            fh.write(stale)  # crash-between-writes simulation
        step, restored = ckpt.restore_round({"a": np.zeros(2, np.float32)})
        assert step == 4
        np.testing.assert_allclose(restored["a"], 4.0)
        ckpt.close()

    def test_corrupt_aggregator_state_fails_actionably(self, tmp_path):
        ckpt = self._saved(tmp_path)
        with open(ckpt.aggregator_path, "wb") as fh:
            fh.write(b"not an npz")
        with pytest.raises(CheckpointIntegrityError, match="aggregator"):
            ckpt.load_aggregator_state()
        ckpt.close()

    def test_server_resume_emits_checkpoint_invalid_event(self, tmp_path):
        m = MetricsLogger(validate=True)
        crashed = FederatedServer(
            min_clients=1, family="avitm", model_kwargs=MODEL_KWARGS,
            save_dir=str(tmp_path),
        )
        from gfedntm_tpu.data.vocab import Vocabulary

        tokens = tuple(f"tok{i:02d}" for i in range(30))
        crashed.global_vocab = Vocabulary(tokens)
        crashed.template = build_template_model(
            "avitm", len(tokens), MODEL_KWARGS
        )
        crashed.last_average = dict(crashed._shared_template())
        crashed.global_iterations = 3
        crashed._save_round_checkpoint()
        meta_path = crashed._checkpointer().meta_path
        with open(meta_path, "w") as fh:
            fh.write("{broken")
        resumed = FederatedServer(
            min_clients=1, family="avitm", model_kwargs=MODEL_KWARGS,
            save_dir=str(tmp_path), metrics=m,
        )
        with pytest.raises(CheckpointIntegrityError):
            resumed.restore_from_checkpoint()
        assert m.registry.counter("checkpoint_invalid").value == 1
        assert m.events("checkpoint_invalid")[0]["reason"]


# ---- registry probation reasons ---------------------------------------------

def test_mark_suspect_records_reason_in_snapshot():
    fed = Federation(min_clients=1)
    fed.connect_vocab(1, ("a",), 2.0)
    fed.connect_ready(1, "localhost:1")
    assert fed.mark_suspect(1, "localhost:1", 0, reason="poisoned") \
        == SUSPECT
    snap = fed.membership_snapshot()[0]
    assert snap["suspect_reason"] == "poisoned"
    assert fed.mark_recovered(1)
    assert fed.membership_snapshot()[0]["suspect_reason"] == ""
    fed.mark_suspect(1, "localhost:1", 1, probation_rounds=1,
                     reason="divergence")
    snap = fed.membership_snapshot()[0]
    assert snap["status"] == DROPPED and snap["suspect_reason"] == "divergence"


# ---- server-level admission wiring ------------------------------------------

class TestServerAdmission:
    def _server(self, **kw):
        base = dict(min_clients=1, family="avitm",
                    model_kwargs=MODEL_KWARGS,
                    metrics=MetricsLogger(validate=True))
        base.update(kw)
        server = FederatedServer(**base)
        server.template = build_template_model("avitm", 30, MODEL_KWARGS)
        return server

    def _reply(self, client_id, snap, loss=1.0):
        return pb.StepReply(
            client_id=client_id, shared=codec.flatdict_to_bundle(snap),
            loss=loss, nr_samples=4.0,
        )

    def test_nan_reply_rejected_then_probation_then_drop(self):
        from gfedntm_tpu.federation.registry import ClientRecord

        server = self._server(probation_rounds=2)
        server.federation.connect_vocab(1, ("a",), 4.0)
        server.federation.connect_ready(1, "localhost:1")
        rec = server.federation.get_clients()[0]
        tmpl = server._shared_template()
        poisoned = {
            k: np.full_like(v, np.nan) if v.dtype.kind == "f" else v
            for k, v in tmpl.items()
        }
        good_rec = ClientRecord(2, nr_samples=4.0)

        out = server._collect_snapshots(
            [(rec, self._reply(1, poisoned)),
             (good_rec, self._reply(2, tmpl))], iteration=0,
        )
        assert len(out) == 1  # round 0: rejected, streak 1, still ACTIVE
        assert rec.status == "active"
        out = server._collect_snapshots(
            [(rec, self._reply(1, poisoned)),
             (good_rec, self._reply(2, tmpl))], iteration=1,
        )
        assert len(out) == 1  # round 1: streak 2 -> suspect("poisoned")
        assert rec.status == SUSPECT and rec.suspect_reason == "poisoned"
        out = server._collect_snapshots(
            [(rec, self._reply(1, poisoned)),
             (good_rec, self._reply(2, tmpl))], iteration=2,
        )
        assert rec.status == DROPPED  # probation_rounds=2 exhausted
        m = server.metrics
        assert m.registry.counter("updates_rejected").value == 3
        suspects = m.events("client_suspect")
        assert suspects and all(s["reason"] == "poisoned" for s in suspects)

    def test_recovery_is_admission_scoped(self):
        """A suspect whose RPC succeeds but whose update is rejected must
        NOT recover; one whose update is admitted must."""
        server = self._server()
        server.federation.connect_vocab(1, ("a",), 4.0)
        server.federation.connect_ready(1, "localhost:1")
        rec = server.federation.get_clients()[0]
        server.federation.mark_suspect(1, "localhost:1", 0, reason="poisoned")
        tmpl = server._shared_template()
        poisoned = {
            k: np.full_like(v, np.nan) if v.dtype.kind == "f" else v
            for k, v in tmpl.items()
        }
        server._collect_snapshots(
            [(rec, self._reply(1, poisoned))], iteration=1,
            was_suspect=frozenset({1}),
        )
        assert rec.status == SUSPECT  # polite poisoner stays on probation
        server._collect_snapshots(
            [(rec, self._reply(1, tmpl))], iteration=2,
            was_suspect=frozenset({1}),
        )
        assert rec.status == "active"
        m = server.metrics
        assert m.registry.counter("client_recoveries").value == 1
        assert m.events("client_recovered")[0]["round"] == 2

    def test_status_exposes_data_plane(self):
        server = self._server(max_update_norm=9.0)
        status = server._status()
        dp = status["data_plane"]
        assert dp["sanitize"] is True
        assert dp["max_update_norm"] == 9.0
        assert dp["updates_rejected"] == 0
        assert dp["guardian_healthy"] is True
        off = self._server(sanitize=False, divergence_patience=0)
        dp = off._status()["data_plane"]
        assert dp["sanitize"] is False and dp["guardian_healthy"] is None


# ---- CLI knobs --------------------------------------------------------------

def test_parser_data_plane_flags():
    p = build_parser()
    args = p.parse_args([])
    assert args.robust_aggregator is None
    assert args.max_update_norm is None
    assert args.outlier_mad_k == 4.0
    assert args.divergence_patience == 3
    args = p.parse_args([
        "--robust_aggregator", "trimmed_mean:0.25",
        "--max_update_norm", "50", "--outlier_mad_k", "0",
        "--divergence_patience", "2",
    ])
    assert args.robust_aggregator == "trimmed_mean:0.25"
    assert args.max_update_norm == 50.0
    assert args.outlier_mad_k == 0.0 and args.divergence_patience == 2


# ---- bf16 BoW count screen (ADVICE r5) --------------------------------------

def test_bf16_bow_count_warning(caplog):
    import logging

    from gfedntm_tpu.train.steps import check_bf16_bow_counts

    logger = logging.getLogger("bf16check")
    with caplog.at_level(logging.WARNING):
        assert not check_bf16_bow_counts(
            np.full((4, 8), 256.0, np.float32), logger
        )
    assert not caplog.records
    with caplog.at_level(logging.WARNING):
        assert check_bf16_bow_counts(
            np.full((4, 8), 257.0, np.float32), logger
        )
    assert any("quantized" in r.message for r in caplog.records)
    assert not check_bf16_bow_counts(np.zeros((0, 8)), logger)


def test_bf16_model_screens_corpus_once(caplog):
    import logging

    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.models.avitm import AVITM

    model = AVITM(input_size=16, n_components=2, hidden_sizes=(4,),
                  batch_size=4, num_epochs=1, compute_dtype="bfloat16")
    X = np.zeros((4, 16), np.float32)
    X[0, 0] = 300.0
    ds = BowDataset(X=X, idx2token={i: f"t{i}" for i in range(16)})
    with caplog.at_level(logging.WARNING):
        model._device_data(ds)
        model._device_data(ds)  # second call: already screened
    warns = [r for r in caplog.records if "quantized" in r.message]
    assert len(warns) == 1


# ---- chaos: poisoned federations over real gRPC -----------------------------

def _corpora(n_clients, docs, seed=0):
    rng = np.random.default_rng(seed)
    words = [f"tok{i:02d}" for i in range(45)]
    return [
        RawCorpus(documents=[
            " ".join(rng.choice(words, size=12)) for _ in range(docs)
        ])
        for _ in range(n_clients)
    ]


def _run_federation(tmp_path, corpora, tag, *, injector=None, metrics=None,
                    poisoned_peer=None, payload=None, fault_times=64,
                    fault_skip=0, **server_kw):
    """Drive one in-process federation to completion; returns (server,
    clients). ``poisoned_peer`` scripts a payload fault against that
    client's TrainStep replies (all of them by default; ``fault_skip``
    lets that many clean rounds pass first)."""
    if injector is None and poisoned_peer is not None:
        injector = FaultInjector(seed=0, metrics=metrics)
    if poisoned_peer is not None:
        injector.script("TrainStep", kind="corrupt", payload=payload,
                        times=fault_times, peer=poisoned_peer,
                        skip=fault_skip)
    base = dict(
        min_clients=len(corpora), family="avitm",
        model_kwargs=MODEL_KWARGS, max_iters=40,
        save_dir=str(tmp_path / f"{tag}-server"), metrics=metrics,
        fault_injector=injector, checkpoint_every=0, round_backoff_s=0.05,
    )
    base.update(server_kw)
    server = FederatedServer(**base)
    addr = server.start("[::]:0")
    clients = [
        Client(client_id=c + 1, corpus=corpus, server_address=addr,
               max_features=45, save_dir=str(tmp_path / f"{tag}-c{c + 1}"),
               metrics=metrics)
        for c, corpus in enumerate(corpora)
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    try:
        assert server.wait_done(timeout=600), f"{tag}: did not finish"
        for t in threads:
            t.join(timeout=60)
    finally:
        server.stop()
        for c in clients:
            c.shutdown()
    return server, clients


@pytest.mark.chaos
@pytest.mark.parametrize("robust,payload,reason", [
    ("trimmed_mean:0.25", "scale:100", "norm_outlier"),
    ("median", "nan", "nonfinite"),
    ("krum:1", "scale:100", "norm_outlier"),
])
def test_poisoned_client_rejected_robust_matches_honest_baseline(
    tmp_path, robust, payload, reason,
):
    """ISSUE 5 acceptance: a 4-client federation where client 4 emits NaN /
    100x-scaled updates finishes with a final global model matching the
    3-honest-client baseline (same robust aggregator), and the poisoned
    client lands in probation with reason="poisoned"."""
    corpora = _corpora(4, docs=24, seed=5)
    baseline_server, _ = _run_federation(
        tmp_path, corpora[:3], f"base-{reason}",
        robust_aggregator=robust, outlier_mad_k=6.0,
    )
    base_betas = baseline_server.global_betas
    assert base_betas is not None and np.isfinite(base_betas).all()

    metrics = MetricsLogger(validate=True)
    server, clients = _run_federation(
        tmp_path, corpora, f"poison-{reason}", metrics=metrics,
        poisoned_peer="client4", payload=payload,
        robust_aggregator=robust, outlier_mad_k=6.0,
    )
    assert server.global_betas is not None
    np.testing.assert_allclose(
        server.global_betas, base_betas, rtol=1e-4, atol=1e-5,
    )
    # the poisoned client's updates were rejected with the expected reason
    rejections = metrics.events("update_rejected")
    assert rejections and all(
        e["client"] == 4 and e["reason"] == reason for e in rejections
    )
    assert metrics.registry.counter("updates_rejected").value >= 2
    # ... and it landed in probation (reason "poisoned"), eventually the
    # permanent drop — while the honest clients trained to completion
    rec = {r.client_id: r for r in server.federation.get_clients()}[4]
    assert rec.status in (SUSPECT, DROPPED)
    assert rec.suspect_reason == "poisoned"
    suspects = metrics.events("client_suspect")
    assert suspects and all(s["reason"] == "poisoned" for s in suspects)
    for c in clients[:3]:
        assert c.stepper.finished
    # visible in /status too
    dp = server._status()["data_plane"]
    assert dp["updates_rejected"] >= 2
    assert dp["rejections_by_client"].get(4, 0) >= 2


@pytest.mark.chaos
def test_plain_fedavg_without_gate_degrades(tmp_path):
    """The control leg: with the admission gate disabled and no robust
    aggregator, one NaN-emitting client poisons the global model in one
    round — the degradation the data plane exists to prevent."""
    metrics = MetricsLogger(validate=True)
    kwargs = dict(MODEL_KWARGS, num_epochs=1)
    server, _clients = _run_federation(
        tmp_path, _corpora(4, docs=16, seed=5), "degrade", metrics=metrics,
        poisoned_peer="client4", payload="nan",
        model_kwargs=kwargs, sanitize=False, divergence_patience=0,
    )
    assert server.global_betas is not None
    assert not np.isfinite(server.global_betas).all()
    assert metrics.registry.counter("updates_rejected").value == 0


@pytest.mark.chaos
def test_divergence_rollback_then_recovery(tmp_path):
    """ISSUE 5 acceptance: a scripted one-shot NaN poisoning (gate off, so
    it reaches the aggregate) triggers exactly ONE rollback to the last
    good checkpointed round; the re-broadcast resets the delta-reference
    cache (self-contained push, zero codec_ref_miss) and training resumes
    to completion with a finite model."""
    metrics = MetricsLogger(validate=True)
    kwargs = dict(MODEL_KWARGS, num_epochs=3)  # 9 rounds of 3 steps each
    server, clients = _run_federation(
        tmp_path, _corpora(3, docs=24, seed=9), "rollback", metrics=metrics,
        poisoned_peer="client1", payload="nan", fault_times=1,
        fault_skip=4,  # rounds 0-3 clean -> checkpoints at 2 and 4
        model_kwargs=kwargs, sanitize=False,
        checkpoint_every=2, wire_codec="delta",
    )
    # exactly one rollback, to the last good checkpointed round (4), with
    # the immediate non-finite verdict
    rollbacks = metrics.events("divergence_rollback")
    assert len(rollbacks) == 1
    assert rollbacks[0]["reason"] == "nonfinite_global"
    assert rollbacks[0]["round"] == 4
    assert rollbacks[0]["restored_round"] == 4
    assert metrics.registry.counter("divergence_rollbacks").value == 1
    # the re-broadcast reset BOTH server-side codec sessions AND (via the
    # push's reset_session flag) every recipient's uplink+downlink pair
    # (3 clients x 2), and nothing ever mis-decoded against the
    # rolled-back state
    assert metrics.registry.counter("codec_resets").value == 2 + 3 * 2
    assert metrics.registry.counter("codec_ref_miss").value == 0
    # training resumed past the rollback to completion, model finite
    assert server.global_iterations == 9
    assert server.global_betas is not None
    assert np.isfinite(server.global_betas).all()
    for c in clients:
        assert c.stepper.finished and c.results is not None
    # the periodic checkpoints continued after recovery
    assert server._checkpointer().latest_round() > 4
