"""Driver-robustness of bench.py's top-level orchestration.

VERDICT r4 weak #1: the round-4 official record silently degraded to CPU
after two tunnel timeouts while the real TPU number lived only in prose.
The bench now (a) banks every successful live-TPU run as a committed
artifact and (b) when live TPU is unreachable, emits that banked artifact
with explicit ``provenance: cached`` instead of a CPU number presented as
the round's result. These tests pin that logic (pure host-side — no jax).
"""

import json
import os
import sys

import bench as bench_mod

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"),
)
import bench_schema  # noqa: E402


def _write_artifact(path, backend="tpu", value=123456.7):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(
            {
                "metric": "federated_prodlda_5client_throughput",
                "value": value,
                "unit": "docs/s",
                "backend": backend,
                "captured_at_commit": "abc123def456",
            },
            f,
        )


class TestCachedFallback:
    def test_cached_summary_marks_provenance(self, tmp_path, monkeypatch):
        artifact = tmp_path / "bench_tpu" / "bench_latest.json"
        _write_artifact(str(artifact))
        monkeypatch.setattr(bench_mod, "_TPU_ARTIFACT", str(artifact))
        summary = bench_mod._cached_tpu_summary()
        assert summary is not None
        assert summary["provenance"] == "cached"
        assert summary["backend"] == "tpu"
        assert "abc123def456"[:12] in summary["provenance_note"]

    def test_no_artifact_returns_none(self, tmp_path, monkeypatch):
        monkeypatch.setattr(
            bench_mod, "_TPU_ARTIFACT", str(tmp_path / "missing.json")
        )
        assert bench_mod._cached_tpu_summary() is None

    def test_cpu_artifact_rejected(self, tmp_path, monkeypatch):
        """A banked CPU-backend record must never be served as the TPU
        fallback — that would re-create the silent-degradation bug."""
        artifact = tmp_path / "bench_latest.json"
        _write_artifact(str(artifact), backend="cpu")
        monkeypatch.setattr(bench_mod, "_TPU_ARTIFACT", str(artifact))
        assert bench_mod._cached_tpu_summary() is None

    def test_corrupt_artifact_returns_none(self, tmp_path, monkeypatch):
        artifact = tmp_path / "bench_latest.json"
        artifact.write_text("{not json")
        monkeypatch.setattr(bench_mod, "_TPU_ARTIFACT", str(artifact))
        assert bench_mod._cached_tpu_summary() is None


class TestPersistArtifact:
    def test_persist_writes_record(self, tmp_path, monkeypatch):
        artifact = tmp_path / "bench_tpu" / "bench_latest.json"
        monkeypatch.setattr(bench_mod, "_TPU_ARTIFACT", str(artifact))
        monkeypatch.setenv("BENCH_NO_GIT", "1")
        bench_mod._persist_tpu_artifact(
            {"metric": "m", "value": 1.0, "backend": "tpu"}
        )
        record = json.loads(artifact.read_text())
        assert record["backend"] == "tpu"
        assert record["captured_unix_time"] > 0
        # The banked record round-trips through the cached path.
        summary = bench_mod._cached_tpu_summary()
        assert summary["provenance"] == "cached"

    def test_persist_never_raises(self, monkeypatch):
        monkeypatch.setattr(
            bench_mod, "_TPU_ARTIFACT", "/proc/definitely/not/writable.json"
        )
        monkeypatch.setenv("BENCH_NO_GIT", "1")
        bench_mod._persist_tpu_artifact({"backend": "tpu"})  # must not raise


class TestMainOrchestration:
    """End-to-end driver-path decisions of bench.main(): live success
    banks the artifact; a dead tunnel escalates deadlines then emits the
    cached artifact instead of a CPU number."""

    def _run_main(self, monkeypatch, capsys, phase_results, backend="axon",
                  artifact_dir=None, budget_s=3600.0):
        calls = []

        def fake_run_phase(phase, bk, timeout_s, retries=1, failures=None):
            calls.append((phase, bk, timeout_s))
            result = phase_results.pop(0) if phase_results else None
            if result is None and failures is not None:
                failures.append(dict(
                    phase=phase, backend=bk,
                    timeout_s=round(timeout_s, 1), reason="timeout",
                    attempt=1,
                ))
            return result

        monkeypatch.setattr(bench_mod, "_probe_backend", lambda: backend)
        monkeypatch.setattr(bench_mod, "_run_phase", fake_run_phase)
        monkeypatch.setattr(bench_mod.sys, "argv", ["bench.py"])
        monkeypatch.setenv("BENCH_NO_GIT", "1")
        # Phases are faked (instant), so a generous default budget keeps
        # these tests about orchestration order, not budget clamping; the
        # budget tests below pin the clamping itself.
        monkeypatch.setenv("BENCH_BUDGET_S", str(budget_s))
        if artifact_dir is not None:
            monkeypatch.setattr(
                bench_mod, "_TPU_ARTIFACT",
                str(artifact_dir / "bench_latest.json"),
            )
        bench_mod.main()
        out = capsys.readouterr().out.strip().splitlines()[-1]
        return json.loads(out), calls

    def test_live_tpu_success_banks_artifact(self, monkeypatch, capsys,
                                             tmp_path):
        summary = {"metric": "m", "value": 9.0, "backend": "tpu"}
        fused = {"V16384_B64": {"parity": True}}
        result, calls = self._run_main(
            monkeypatch, capsys, [dict(summary), fused],
            artifact_dir=tmp_path,
        )
        assert result["provenance"] == "live"
        assert result["fused_largev"] == fused
        banked = json.loads((tmp_path / "bench_latest.json").read_text())
        assert banked["backend"] == "tpu"
        assert banked["fused_largev"] == fused  # re-banked after fused phase

    def test_live_after_escalated_retry_records_abandoned_attempt(
        self, monkeypatch, capsys, tmp_path
    ):
        """A first accelerator attempt that times out must stay on the
        record even when the 2x escalated retry succeeds: a live summary
        after a timeout must not erase the timeout (the r03-r05
        diagnosis evidence lives in accel_attempts)."""
        summary = {"metric": "m", "value": 9.0, "backend": "tpu"}
        result, calls = self._run_main(
            monkeypatch, capsys, [None, dict(summary), None],
            artifact_dir=tmp_path,
        )
        assert result["provenance"] == "live"
        assert [c[1] for c in calls[:2]] == ["axon", "axon"]
        attempts = result["accel_attempts"]
        assert attempts and attempts[0]["reason"] == "timeout"
        assert attempts[0]["phase"] == "run"

    def test_dead_tunnel_escalates_then_uses_cached(self, monkeypatch,
                                                    capsys, tmp_path):
        _write_artifact(str(tmp_path / "bench_latest.json"), value=777.0)
        result, calls = self._run_main(
            monkeypatch, capsys, [None, None], artifact_dir=tmp_path,
        )
        assert result["provenance"] == "cached"
        assert result["value"] == 777.0
        # two live attempts on the TPU backend, second with 2x deadline
        assert [c[1] for c in calls] == ["axon", "axon"]
        assert calls[1][2] == 2 * calls[0][2]

    def test_dead_tunnel_no_artifact_degrades_to_cpu(self, monkeypatch,
                                                     capsys, tmp_path):
        cpu_summary = {"metric": "m", "value": 1.0, "backend": "cpu"}
        result, calls = self._run_main(
            monkeypatch, capsys, [None, None, cpu_summary, None],
            artifact_dir=tmp_path / "missing",
        )
        assert result["provenance"] == "live-cpu-degraded"
        assert result["backend"] == "cpu"

    def test_budget_clamps_deadlines_and_skips_escalation(
        self, monkeypatch, capsys, tmp_path
    ):
        """BENCH_r01-r05 regression: the old internal schedule (720 s +
        1440 s escalation + 1800 s CPU fallback) could legally run ~65 min
        under the harness's hard 720 s deadline -> rc=124 and no JSON.
        Under a small BENCH_BUDGET_S every deadline is clamped, the 2x
        escalation is skipped when it cannot fit, and the run still emits
        a parseable summary."""
        cpu_summary = {"metric": "m", "value": 1.0, "backend": "cpu"}
        result, calls = self._run_main(
            monkeypatch, capsys, [None, cpu_summary, None],
            artifact_dir=tmp_path / "missing", budget_s=300.0,
        )
        assert result["provenance"] == "live-cpu-degraded"
        # one TPU attempt (clamped below the 720 s default), then straight
        # to the CPU fallback — no 2x escalation inside a 300 s budget
        assert [c[1] for c in calls[:2]] == ["axon", "cpu"]
        assert calls[0][2] <= 300.0 - 240.0 + 1.0 or calls[0][2] == 60.0
        assert all(c[2] <= 300.0 for c in calls)

    def test_budget_exhaustion_skips_fused_phase(self, monkeypatch, capsys,
                                                 tmp_path):
        """A main phase that ate the whole budget leaves a summary whose
        fused_largev_error says the phase was skipped for budget — not a
        silent absence, and no over-budget subprocess."""
        summary = {"metric": "m", "value": 9.0, "backend": "cpu"}
        result, calls = self._run_main(
            monkeypatch, capsys, [dict(summary)],
            artifact_dir=tmp_path, backend="cpu", budget_s=60.0,
        )
        assert [c[0] for c in calls] == ["run"]  # fused never launched
        assert "BENCH_BUDGET_S" in result["fused_largev_error"]

    def test_cpu_degradation_cites_committed_tpu_evidence(
        self, monkeypatch, capsys, tmp_path
    ):
        """With no banked artifact, the degraded record must point at the
        strongest committed TPU evidence (step_time_probe) so the
        official capture is self-describing."""
        cpu_summary = {"metric": "m", "value": 1.0, "backend": "cpu"}
        result, _ = self._run_main(
            monkeypatch, capsys, [None, None, cpu_summary, None],
            artifact_dir=tmp_path / "missing",
        )
        ev = result.get("strongest_committed_tpu_evidence")
        assert ev is not None and ev["backend"] == "tpu"
        assert ev["docs_per_s"] > 0

    def test_degraded_record_names_abandoned_accel_attempts(
        self, monkeypatch, capsys, tmp_path
    ):
        """ISSUE 6 satellite: a CPU-degraded headline must record WHY —
        the abandoned accelerator attempts with their sub-deadlines and
        reasons (accel_timeout_phase / accel_attempts), so r03-r05-style
        silent CPU numbers cannot recur."""
        cpu_summary = {"metric": "m", "value": 1.0, "backend": "cpu"}
        result, _ = self._run_main(
            monkeypatch, capsys, [None, None, cpu_summary, None],
            artifact_dir=tmp_path / "missing",
        )
        assert result["backend"] == "cpu"
        assert result["provenance"] == "live-cpu-degraded"
        assert result["accel_timeout_phase"] == "run"
        attempts = result["accel_attempts"]
        assert attempts and all(a["phase"] == "run" for a in attempts)
        assert all(a["reason"] == "timeout" for a in attempts)
        assert all(a["timeout_s"] > 0 for a in attempts)

    def test_all_attempts_dead_ships_best_partial(self, monkeypatch,
                                                  capsys, tmp_path):
        """ISSUE 12 satellite: when EVERY live attempt hangs, the
        completed stages' evidence must still ship — the best partial
        summary any attempt flushed becomes the record (provenance:
        partial), accel_timeout_phase names the hung STAGE, and the
        per-attempt breadcrumbs survive with their bulky partial copies
        stripped. BENCH_r05's rc=124 / parsed:null (all evidence lost)
        is the regression this pins."""
        partial = {
            "metric": "bench_run_partial", "value": 1810.4,
            "unit": "docs/s", "vs_baseline": None, "backend": "cpu",
            "partial": True,
            "stage_order": ["backend_init", "data_staging"],
            "run_stages": {
                "backend_init": {"seconds": 2.1, "platform": "cpu"},
                "data_staging": {"seconds": 7.9, "docs": 2500},
            },
        }
        calls = []

        def fake_run_phase(phase, bk, timeout_s, retries=1, failures=None):
            calls.append((phase, bk))
            if failures is not None:
                failures.append(dict(
                    phase=phase, backend=bk,
                    timeout_s=round(timeout_s, 1),
                    reason="stage_timeout", attempt=1,
                    stage="first_step_compile",
                    stages_completed=list(partial["stage_order"]),
                    partial=dict(partial),
                ))
            return None

        monkeypatch.setattr(bench_mod, "_probe_backend", lambda: "axon")
        monkeypatch.setattr(bench_mod, "_run_phase", fake_run_phase)
        monkeypatch.setattr(bench_mod.sys, "argv", ["bench.py"])
        monkeypatch.setenv("BENCH_NO_GIT", "1")
        monkeypatch.setenv("BENCH_BUDGET_S", "3600")
        monkeypatch.setattr(
            bench_mod, "_TPU_ARTIFACT", str(tmp_path / "missing.json")
        )
        bench_mod.main()
        out = capsys.readouterr().out.strip().splitlines()[-1]
        result = json.loads(out)
        assert result["provenance"] == "partial"
        assert result["value"] == 1810.4
        assert result["run_stages"]["data_staging"]["docs"] == 2500
        assert result["accel_timeout_phase"] == "first_step_compile"
        attempts = result["accel_attempts"]
        assert attempts and all("partial" not in a for a in attempts)
        assert all(
            a["stages_completed"] == partial["stage_order"]
            for a in attempts
        )
        # The shipped partial satisfies both artifact shape contracts.
        assert bench_schema.validate(result, "bench_partial") == []
        assert bench_schema.validate(result, "bench") == []


class TestStagedWatchdog:
    """The staged run-phase machinery itself, against REAL subprocesses:
    per-stage sub-deadlines enforced from outside, completed stages
    flushed before the kill, the hung stage named (ISSUE 12 tentpole)."""

    _STAGED_SCRIPT = """\
import os, sys, time
sys.path.insert(0, {repo!r})
import bench

log = bench.StageLog(backend="cpu")
with log.stage("backend_init") as p:
    p.update(platform="cpu", devices=8)
with log.stage("data_staging") as p:
    p.update(docs=2500, docs_per_s=1810.4)
with log.stage("first_step_compile") as p:   # BENCH_FAKE_HANG_STAGE hangs here
    p.update(unreachable=True)
print("DONE")
"""

    def _spawn_staged(self, tmp_path, hang_stage, deadline_s="1.0"):
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        stage_path = str(tmp_path / "stages.jsonl")
        partial_path = str(tmp_path / "partial.json")
        env = dict(
            os.environ,
            BENCH_STAGE_PATH=stage_path,
            BENCH_PARTIAL_PATH=partial_path,
            BENCH_FAKE_HANG_STAGE=hang_stage,
        )
        env[f"BENCH_STAGE_TIMEOUT_{hang_stage.upper()}"] = deadline_s
        proc = subprocess.Popen(
            [sys.executable, "-c",
             self._STAGED_SCRIPT.format(repo=repo)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return proc, stage_path, partial_path, env

    def test_hung_stage_killed_at_its_own_deadline(self, tmp_path,
                                                    monkeypatch):
        """The monkeypatched-hanging-stage regression: a stage that hangs
        (BENCH_FAKE_HANG_STAGE, the documented test hook) is killed at
        ITS deadline — not the whole-phase backstop — and the watcher
        returns its name; the stages that completed are all on disk."""
        proc, stage_path, partial_path, env = self._spawn_staged(
            tmp_path, "first_step_compile"
        )
        for k in ("BENCH_STAGE_TIMEOUT_FIRST_STEP_COMPILE",):
            monkeypatch.setenv(k, env[k])
        try:
            hung = bench_mod._watch_stages(
                proc, stage_path, timeout_s=120.0
            )
        finally:
            proc.kill()
            proc.wait()
        assert hung is not None
        stage, waited = hung
        assert stage == "first_step_compile"
        assert 1.0 <= waited < 30.0  # its 1 s deadline, not the 120 s backstop
        done, inflight = bench_mod._stage_view(
            bench_mod._read_stage_file(stage_path)
        )
        assert done == ["backend_init", "data_staging"]
        assert inflight is not None and inflight[0] == "first_step_compile"
        # The partial flushed after every completed stage still ships —
        # schema-valid, carrying each completed stage's timings/payload.
        partial = bench_mod._read_partial(partial_path)
        assert partial is not None
        assert bench_schema.validate(partial, "bench_partial") == []
        assert partial["stage_order"] == ["backend_init", "data_staging"]
        assert partial["run_stages"]["data_staging"]["docs"] == 2500
        assert partial["value"] == 1810.4  # best completed-stage throughput

    def test_clean_exit_returns_none(self, tmp_path):
        """No hang -> the watcher reports a clean exit and every stage's
        done record (and the final partial) is on disk."""
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        stage_path = str(tmp_path / "stages.jsonl")
        env = dict(os.environ, BENCH_STAGE_PATH=stage_path)
        env.pop("BENCH_FAKE_HANG_STAGE", None)
        proc = subprocess.Popen(
            [sys.executable, "-c",
             self._STAGED_SCRIPT.format(repo=repo)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            assert bench_mod._watch_stages(
                proc, stage_path, timeout_s=120.0
            ) is None
        finally:
            proc.kill()
            proc.wait()
        done, inflight = bench_mod._stage_view(
            bench_mod._read_stage_file(stage_path)
        )
        assert done == [
            "backend_init", "data_staging", "first_step_compile",
        ]
        assert inflight is None

    def test_stage_view_tolerates_torn_tail(self, tmp_path):
        """The writer can be SIGKILLed mid-append: a torn final line must
        not cost the parsed records before it."""
        p = tmp_path / "stages.jsonl"
        p.write_text(
            json.dumps({"stage": "backend_init", "status": "begin",
                        "wall_time": 1.0}) + "\n"
            + json.dumps({"stage": "backend_init", "status": "done",
                          "seconds": 2.0, "wall_time": 3.0}) + "\n"
            + '{"stage": "data_st'  # torn mid-append
        )
        done, inflight = bench_mod._stage_view(
            bench_mod._read_stage_file(str(p))
        )
        assert done == ["backend_init"]
        assert inflight is None

    def test_hung_stage_and_best_partial_helpers(self):
        att = [
            {"reason": "rc", "stage": None},
            {"reason": "stage_timeout", "stage": "backend_init",
             "partial": {"run_stages": {"a": {}}}},
            {"reason": "stage_timeout", "stage": "data_staging",
             "partial": {"run_stages": {"a": {}, "b": {}}}},
        ]
        assert bench_mod._hung_stage(att) == "data_staging"
        assert bench_mod._hung_stage([]) is None
        assert bench_mod._hung_stage(None) is None
        best = bench_mod._best_partial(att)
        assert best is not None and len(best["run_stages"]) == 2
        assert bench_mod._best_partial(None) is None
        stripped = bench_mod._strip_partials(att)
        assert all("partial" not in a for a in stripped)
        assert [a.get("stage") for a in stripped] == [
            None, "backend_init", "data_staging",
        ]


class TestBenchSchema:
    """scripts/bench_schema.py — the shared artifact-shape contract
    (ISSUE 12 satellite: bench.py / agg_microbench.py / scale_bench.py
    all emit through it so fields can't silently drift)."""

    def test_valid_bench_summary(self):
        ok = {"metric": "m", "value": 1.0, "unit": "docs/s",
              "vs_baseline": 2.0, "backend": "cpu"}
        assert bench_schema.validate(ok, "bench") == []
        assert bench_schema.require(ok, "bench") is ok

    def test_missing_field_named(self):
        problems = bench_schema.validate(
            {"metric": "m", "value": 1.0}, "bench"
        )
        assert any("vs_baseline" in p for p in problems)
        assert any("backend" in p for p in problems)

    def test_conditional_companions(self):
        """An abandoned accelerator attempt must ship its evidence: a
        summary claiming accel_timeout_phase without accel_attempts (or
        partial without run_stages) is a schema violation."""
        base = {"metric": "m", "value": 0.0, "unit": "docs/s",
                "vs_baseline": None, "backend": "cpu"}
        bad = dict(base, accel_timeout_phase="backend_init")
        assert any(
            "accel_attempts" in p
            for p in bench_schema.validate(bad, "bench")
        )
        good = dict(bad, accel_attempts=[{"reason": "stage_timeout"}])
        assert bench_schema.validate(good, "bench") == []
        bad2 = dict(base, partial=True)
        assert any(
            "run_stages" in p for p in bench_schema.validate(bad2, "bench")
        )

    def test_row_validation_keys_on_metric(self):
        row = {"metric": "agg_estimator_wall_ms", "estimator": "mean",
               "backend": "numpy", "n_clients": 4, "d": 1000,
               "wall_ms": 1.5}
        assert bench_schema.validate_row(row) == []
        assert bench_schema.validate_row({"metric": "nope"}) != []
        del row["wall_ms"]
        assert bench_schema.validate_row(row) != []

    def test_require_raises_and_unknown_kind(self):
        import pytest

        with pytest.raises(ValueError, match="backend"):
            bench_schema.require({"metric": "m"}, "bench")
        assert bench_schema.validate({}, "no_such_kind") != []
        assert bench_schema.validate("not a dict", "bench") != []
