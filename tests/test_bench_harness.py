"""Driver-robustness of bench.py's top-level orchestration.

VERDICT r4 weak #1: the round-4 official record silently degraded to CPU
after two tunnel timeouts while the real TPU number lived only in prose.
The bench now (a) banks every successful live-TPU run as a committed
artifact and (b) when live TPU is unreachable, emits that banked artifact
with explicit ``provenance: cached`` instead of a CPU number presented as
the round's result. These tests pin that logic (pure host-side — no jax).
"""

import json
import os

import bench as bench_mod


def _write_artifact(path, backend="tpu", value=123456.7):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(
            {
                "metric": "federated_prodlda_5client_throughput",
                "value": value,
                "unit": "docs/s",
                "backend": backend,
                "captured_at_commit": "abc123def456",
            },
            f,
        )


class TestCachedFallback:
    def test_cached_summary_marks_provenance(self, tmp_path, monkeypatch):
        artifact = tmp_path / "bench_tpu" / "bench_latest.json"
        _write_artifact(str(artifact))
        monkeypatch.setattr(bench_mod, "_TPU_ARTIFACT", str(artifact))
        summary = bench_mod._cached_tpu_summary()
        assert summary is not None
        assert summary["provenance"] == "cached"
        assert summary["backend"] == "tpu"
        assert "abc123def456"[:12] in summary["provenance_note"]

    def test_no_artifact_returns_none(self, tmp_path, monkeypatch):
        monkeypatch.setattr(
            bench_mod, "_TPU_ARTIFACT", str(tmp_path / "missing.json")
        )
        assert bench_mod._cached_tpu_summary() is None

    def test_cpu_artifact_rejected(self, tmp_path, monkeypatch):
        """A banked CPU-backend record must never be served as the TPU
        fallback — that would re-create the silent-degradation bug."""
        artifact = tmp_path / "bench_latest.json"
        _write_artifact(str(artifact), backend="cpu")
        monkeypatch.setattr(bench_mod, "_TPU_ARTIFACT", str(artifact))
        assert bench_mod._cached_tpu_summary() is None

    def test_corrupt_artifact_returns_none(self, tmp_path, monkeypatch):
        artifact = tmp_path / "bench_latest.json"
        artifact.write_text("{not json")
        monkeypatch.setattr(bench_mod, "_TPU_ARTIFACT", str(artifact))
        assert bench_mod._cached_tpu_summary() is None


class TestPersistArtifact:
    def test_persist_writes_record(self, tmp_path, monkeypatch):
        artifact = tmp_path / "bench_tpu" / "bench_latest.json"
        monkeypatch.setattr(bench_mod, "_TPU_ARTIFACT", str(artifact))
        monkeypatch.setenv("BENCH_NO_GIT", "1")
        bench_mod._persist_tpu_artifact(
            {"metric": "m", "value": 1.0, "backend": "tpu"}
        )
        record = json.loads(artifact.read_text())
        assert record["backend"] == "tpu"
        assert record["captured_unix_time"] > 0
        # The banked record round-trips through the cached path.
        summary = bench_mod._cached_tpu_summary()
        assert summary["provenance"] == "cached"

    def test_persist_never_raises(self, monkeypatch):
        monkeypatch.setattr(
            bench_mod, "_TPU_ARTIFACT", "/proc/definitely/not/writable.json"
        )
        monkeypatch.setenv("BENCH_NO_GIT", "1")
        bench_mod._persist_tpu_artifact({"backend": "tpu"})  # must not raise


class TestMainOrchestration:
    """End-to-end driver-path decisions of bench.main(): live success
    banks the artifact; a dead tunnel escalates deadlines then emits the
    cached artifact instead of a CPU number."""

    def _run_main(self, monkeypatch, capsys, phase_results, backend="axon",
                  artifact_dir=None, budget_s=3600.0):
        calls = []

        def fake_run_phase(phase, bk, timeout_s, retries=1, failures=None):
            calls.append((phase, bk, timeout_s))
            result = phase_results.pop(0) if phase_results else None
            if result is None and failures is not None:
                failures.append(dict(
                    phase=phase, backend=bk,
                    timeout_s=round(timeout_s, 1), reason="timeout",
                    attempt=1,
                ))
            return result

        monkeypatch.setattr(bench_mod, "_probe_backend", lambda: backend)
        monkeypatch.setattr(bench_mod, "_run_phase", fake_run_phase)
        monkeypatch.setattr(bench_mod.sys, "argv", ["bench.py"])
        monkeypatch.setenv("BENCH_NO_GIT", "1")
        # Phases are faked (instant), so a generous default budget keeps
        # these tests about orchestration order, not budget clamping; the
        # budget tests below pin the clamping itself.
        monkeypatch.setenv("BENCH_BUDGET_S", str(budget_s))
        if artifact_dir is not None:
            monkeypatch.setattr(
                bench_mod, "_TPU_ARTIFACT",
                str(artifact_dir / "bench_latest.json"),
            )
        bench_mod.main()
        out = capsys.readouterr().out.strip().splitlines()[-1]
        return json.loads(out), calls

    def test_live_tpu_success_banks_artifact(self, monkeypatch, capsys,
                                             tmp_path):
        summary = {"metric": "m", "value": 9.0, "backend": "tpu"}
        fused = {"V16384_B64": {"parity": True}}
        result, calls = self._run_main(
            monkeypatch, capsys, [dict(summary), fused],
            artifact_dir=tmp_path,
        )
        assert result["provenance"] == "live"
        assert result["fused_largev"] == fused
        banked = json.loads((tmp_path / "bench_latest.json").read_text())
        assert banked["backend"] == "tpu"
        assert banked["fused_largev"] == fused  # re-banked after fused phase

    def test_live_after_escalated_retry_records_abandoned_attempt(
        self, monkeypatch, capsys, tmp_path
    ):
        """A first accelerator attempt that times out must stay on the
        record even when the 2x escalated retry succeeds: a live summary
        after a timeout must not erase the timeout (the r03-r05
        diagnosis evidence lives in accel_attempts)."""
        summary = {"metric": "m", "value": 9.0, "backend": "tpu"}
        result, calls = self._run_main(
            monkeypatch, capsys, [None, dict(summary), None],
            artifact_dir=tmp_path,
        )
        assert result["provenance"] == "live"
        assert [c[1] for c in calls[:2]] == ["axon", "axon"]
        attempts = result["accel_attempts"]
        assert attempts and attempts[0]["reason"] == "timeout"
        assert attempts[0]["phase"] == "run"

    def test_dead_tunnel_escalates_then_uses_cached(self, monkeypatch,
                                                    capsys, tmp_path):
        _write_artifact(str(tmp_path / "bench_latest.json"), value=777.0)
        result, calls = self._run_main(
            monkeypatch, capsys, [None, None], artifact_dir=tmp_path,
        )
        assert result["provenance"] == "cached"
        assert result["value"] == 777.0
        # two live attempts on the TPU backend, second with 2x deadline
        assert [c[1] for c in calls] == ["axon", "axon"]
        assert calls[1][2] == 2 * calls[0][2]

    def test_dead_tunnel_no_artifact_degrades_to_cpu(self, monkeypatch,
                                                     capsys, tmp_path):
        cpu_summary = {"metric": "m", "value": 1.0, "backend": "cpu"}
        result, calls = self._run_main(
            monkeypatch, capsys, [None, None, cpu_summary, None],
            artifact_dir=tmp_path / "missing",
        )
        assert result["provenance"] == "live-cpu-degraded"
        assert result["backend"] == "cpu"

    def test_budget_clamps_deadlines_and_skips_escalation(
        self, monkeypatch, capsys, tmp_path
    ):
        """BENCH_r01-r05 regression: the old internal schedule (720 s +
        1440 s escalation + 1800 s CPU fallback) could legally run ~65 min
        under the harness's hard 720 s deadline -> rc=124 and no JSON.
        Under a small BENCH_BUDGET_S every deadline is clamped, the 2x
        escalation is skipped when it cannot fit, and the run still emits
        a parseable summary."""
        cpu_summary = {"metric": "m", "value": 1.0, "backend": "cpu"}
        result, calls = self._run_main(
            monkeypatch, capsys, [None, cpu_summary, None],
            artifact_dir=tmp_path / "missing", budget_s=300.0,
        )
        assert result["provenance"] == "live-cpu-degraded"
        # one TPU attempt (clamped below the 720 s default), then straight
        # to the CPU fallback — no 2x escalation inside a 300 s budget
        assert [c[1] for c in calls[:2]] == ["axon", "cpu"]
        assert calls[0][2] <= 300.0 - 240.0 + 1.0 or calls[0][2] == 60.0
        assert all(c[2] <= 300.0 for c in calls)

    def test_budget_exhaustion_skips_fused_phase(self, monkeypatch, capsys,
                                                 tmp_path):
        """A main phase that ate the whole budget leaves a summary whose
        fused_largev_error says the phase was skipped for budget — not a
        silent absence, and no over-budget subprocess."""
        summary = {"metric": "m", "value": 9.0, "backend": "cpu"}
        result, calls = self._run_main(
            monkeypatch, capsys, [dict(summary)],
            artifact_dir=tmp_path, backend="cpu", budget_s=60.0,
        )
        assert [c[0] for c in calls] == ["run"]  # fused never launched
        assert "BENCH_BUDGET_S" in result["fused_largev_error"]

    def test_cpu_degradation_cites_committed_tpu_evidence(
        self, monkeypatch, capsys, tmp_path
    ):
        """With no banked artifact, the degraded record must point at the
        strongest committed TPU evidence (step_time_probe) so the
        official capture is self-describing."""
        cpu_summary = {"metric": "m", "value": 1.0, "backend": "cpu"}
        result, _ = self._run_main(
            monkeypatch, capsys, [None, None, cpu_summary, None],
            artifact_dir=tmp_path / "missing",
        )
        ev = result.get("strongest_committed_tpu_evidence")
        assert ev is not None and ev["backend"] == "tpu"
        assert ev["docs_per_s"] > 0

    def test_degraded_record_names_abandoned_accel_attempts(
        self, monkeypatch, capsys, tmp_path
    ):
        """ISSUE 6 satellite: a CPU-degraded headline must record WHY —
        the abandoned accelerator attempts with their sub-deadlines and
        reasons (accel_timeout_phase / accel_attempts), so r03-r05-style
        silent CPU numbers cannot recur."""
        cpu_summary = {"metric": "m", "value": 1.0, "backend": "cpu"}
        result, _ = self._run_main(
            monkeypatch, capsys, [None, None, cpu_summary, None],
            artifact_dir=tmp_path / "missing",
        )
        assert result["backend"] == "cpu"
        assert result["provenance"] == "live-cpu-degraded"
        assert result["accel_timeout_phase"] == "run"
        attempts = result["accel_attempts"]
        assert attempts and all(a["phase"] == "run" for a in attempts)
        assert all(a["reason"] == "timeout" for a in attempts)
        assert all(a["timeout_s"] > 0 for a in attempts)
