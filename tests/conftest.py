"""Test configuration: run the whole suite on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; the federated SPMD path is
exercised on 8 virtual CPU devices instead (SURVEY.md §4: the reference's
docker-compose multi-node test becomes
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` here).

Env vars must be set before the first ``jax`` import, which is why this
happens at conftest import time.
"""

import os

# The runtime image pins JAX_PLATFORMS=axon via sitecustomize, so the env var
# alone is not enough — jax.config is the authoritative override.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    # Registered in pytest.ini too; duplicated here so the suite also runs
    # from a rootdir that misses the ini. pytest.ini's `strict_markers`
    # makes any OTHER marker a collection error.
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end tests (network federation)"
    )
    config.addinivalue_line(
        "markers",
        "chaos: seeded deterministic fault-injection suite "
        "(in-process, tier-1)",
    )


@pytest.fixture
def rng():
    return np.random.default_rng(0)
