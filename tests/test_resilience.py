"""Fault-tolerance suite (tier-1): retry/backoff, client probation, round
quorum, round checkpoint/resume, the client liveness watchdog, and the
deterministic fault-injection harness.

The `chaos` tests run real gRPC federations in-process with scripted,
seeded faults (drop / delay / error-code) injected into the server's
client stubs or the servicer dispatch path — every recovery path is
exercised deterministically, no flaky socket games.
"""

import itertools
import threading
import time

import grpc
import numpy as np
import pytest

from gfedntm_tpu.cli import build_parser
from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.federation import codec, rpc
from gfedntm_tpu.federation.client import Client
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.federation.registry import (
    ACTIVE,
    DROPPED,
    SUSPECT,
    ClientRecord,
    Federation,
)
from gfedntm_tpu.federation.resilience import (
    FaultInjector,
    InjectedRpcError,
    RetryPolicy,
    error_code,
    is_transient,
)
from gfedntm_tpu.federation.server import FederatedServer, build_template_model
from gfedntm_tpu.train.checkpoint import FederationCheckpointer
from gfedntm_tpu.utils.observability import MetricsLogger, read_metrics

UNAVAILABLE = grpc.StatusCode.UNAVAILABLE


# ---- RetryPolicy ------------------------------------------------------------

class TestRetryPolicy:
    def test_transient_classification(self):
        assert is_transient(InjectedRpcError(UNAVAILABLE, "x"))
        assert is_transient(
            InjectedRpcError(grpc.StatusCode.RESOURCE_EXHAUSTED, "x")
        )
        assert is_transient(InjectedRpcError(grpc.StatusCode.ABORTED, "x"))
        assert is_transient(ConnectionRefusedError("refused"))
        # DEADLINE_EXCEEDED is NOT retried at the RPC layer: the call may
        # have executed (TrainStep is not idempotent) — probation handles it.
        assert not is_transient(
            InjectedRpcError(grpc.StatusCode.DEADLINE_EXCEEDED, "x")
        )
        assert not is_transient(ValueError("boom"))
        assert error_code(ValueError("boom")) is None
        assert error_code(InjectedRpcError(UNAVAILABLE, "x")) is UNAVAILABLE

    def test_delays_are_seeded_bounded_and_decorrelated(self):
        p = RetryPolicy(base_delay_s=0.05, max_delay_s=2.0, seed=7)
        a = list(itertools.islice(p.delays(), 8))
        b = list(itertools.islice(p.delays(), 8))
        assert a == b  # same seed -> same jitter sequence
        assert all(0.05 <= d <= 2.0 for d in a)
        q = RetryPolicy(base_delay_s=0.05, max_delay_s=2.0, seed=8)
        assert list(itertools.islice(q.delays(), 8)) != a

    def test_retries_transient_then_succeeds(self):
        m = MetricsLogger(validate=True)
        sleeps = []
        p = RetryPolicy(max_attempts=3, seed=0, metrics=m,
                        sleep=sleeps.append)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise InjectedRpcError(UNAVAILABLE, "blip")
            return 42

        assert p.call(flaky) == 42
        assert calls["n"] == 3
        assert len(sleeps) == 2
        assert m.registry.counter("retry_attempts").value == 2
        assert m.registry.counter("retry_successes").value == 1
        assert m.registry.counter("retry_giveups").value == 0

    def test_permanent_error_not_retried(self):
        p = RetryPolicy(max_attempts=5, sleep=lambda _s: None)
        calls = {"n": 0}

        def bad():
            calls["n"] += 1
            raise ValueError("permanent")

        with pytest.raises(ValueError):
            p.call(bad)
        assert calls["n"] == 1

    def test_exhausted_budget_reraises_and_counts_giveup(self):
        m = MetricsLogger(validate=True)
        p = RetryPolicy(max_attempts=2, seed=0, metrics=m,
                        sleep=lambda _s: None)
        with pytest.raises(InjectedRpcError):
            p.call(lambda: (_ for _ in ()).throw(
                InjectedRpcError(UNAVAILABLE, "down")
            ))
        assert m.registry.counter("retry_attempts").value == 1
        assert m.registry.counter("retry_giveups").value == 1

    def test_rejects_zero_attempts(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)


# ---- FaultInjector ----------------------------------------------------------

class TestFaultInjector:
    def test_scripted_error_fires_n_times_then_clears(self):
        inj = FaultInjector(seed=0)
        inj.script("TrainStep", times=2)
        assert inj.pending("TrainStep") == 2
        for _ in range(2):
            with pytest.raises(InjectedRpcError) as err:
                inj.before_call("svc", "TrainStep")
            assert err.value.code() is UNAVAILABLE
        inj.before_call("svc", "TrainStep")  # script exhausted: no-op
        assert inj.pending() == 0
        assert [f[0] for f in inj.fired] == ["TrainStep", "TrainStep"]

    def test_drop_is_unavailable_and_peer_scoping(self):
        inj = FaultInjector(seed=0)
        spec = inj.script("TrainStep", kind="drop", peer="client2")
        assert spec.kind == "error" and spec.code is UNAVAILABLE
        inj.before_call("svc", "TrainStep", peer="client1")  # other peer
        inj.before_call("svc", "ApplyAggregate", peer="client2")  # other rpc
        with pytest.raises(InjectedRpcError):
            inj.before_call("svc", "TrainStep", peer="client2")
        assert inj.fired == [("TrainStep", "client2", "error")]

    def test_delay_sleeps_and_proceeds(self):
        slept = []
        inj = FaultInjector(seed=0, sleep=slept.append)
        inj.script("TrainStep", kind="delay", delay_s=0.25)
        inj.before_call("svc", "TrainStep")  # no raise
        assert slept == [0.25]

    def test_probabilistic_faults_are_seed_deterministic(self):
        def pattern(seed):
            inj = FaultInjector(seed=seed)
            inj.script("M", times=100, probability=0.5)
            hits = []
            for i in range(30):
                try:
                    inj.before_call("svc", "M")
                    hits.append(False)
                except InjectedRpcError:
                    hits.append(True)
            return hits

        assert pattern(3) == pattern(3)
        assert any(pattern(3)) and not all(pattern(3))

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            FaultInjector().script("M", kind="explode")

    def test_metrics_counter(self):
        m = MetricsLogger(validate=True)
        inj = FaultInjector(seed=0, metrics=m)
        inj.script("M", times=3)
        for _ in range(3):
            with pytest.raises(InjectedRpcError):
                inj.before_call("svc", "M")
        assert m.registry.counter("faults_injected").value == 3


# ---- registry probation + drop/rejoin lifecycle -----------------------------

class TestProbation:
    def _fed_with_client(self, addr="localhost:1111"):
        fed = Federation(min_clients=1)
        fed.connect_vocab(1, ("a", "b"), 4.0)
        fed.connect_ready(1, addr)
        return fed

    def test_suspect_backoff_schedule_then_drop(self):
        fed = self._fed_with_client()
        assert fed.mark_suspect(1, "localhost:1111", 5) == SUSPECT
        rec = fed.get_clients()[0]
        assert rec.consecutive_failures == 1
        assert rec.next_retry_round == 6  # 2**0 rounds out
        # inside the backoff window the client is not polled, but the
        # federation must not end while it is pending
        assert fed.active_clients(5) == []
        assert [c.client_id for c in fed.pending_suspects(5)] == [1]
        assert [c.client_id for c in fed.active_clients(6)] == [1]

        assert fed.mark_suspect(1, "localhost:1111", 6) == SUSPECT
        assert rec.next_retry_round == 8  # 2**1 rounds out
        assert fed.mark_suspect(1, "localhost:1111", 8) == DROPPED
        assert rec.finished and rec.status == DROPPED
        assert fed.active_clients() == []

    def test_recovery_clears_probation(self):
        fed = self._fed_with_client()
        fed.mark_suspect(1, "localhost:1111", 0)
        assert fed.mark_recovered(1) is True
        rec = fed.get_clients()[0]
        assert rec.status == ACTIVE
        assert rec.consecutive_failures == 0 and rec.next_retry_round == 0
        # only a genuine SUSPECT->ACTIVE transition counts as a recovery
        assert fed.mark_recovered(1) is False
        assert fed.mark_recovered(99) is False

    def test_stale_address_failures_ignored_after_rejoin(self):
        """A rejoin changes the serving address; in-flight failures against
        the OLD address must not clobber the fresh registration."""
        fed = self._fed_with_client("localhost:1111")
        fed.connect_ready(1, "localhost:2222")  # rejoined on a new port
        assert fed.mark_suspect(1, "localhost:1111", 3) is None
        fed.mark_dropped(1, "localhost:1111")
        rec = fed.get_clients()[0]
        assert rec.status == ACTIVE and not rec.finished
        # against the CURRENT address both still act
        fed.mark_dropped(1, "localhost:2222")
        assert rec.status == DROPPED and rec.finished

    def test_rejoin_resets_probation_slate(self):
        fed = self._fed_with_client()
        fed.mark_suspect(1, "localhost:1111", 0)
        fed.mark_suspect(1, "localhost:1111", 1)
        fed.connect_ready(1, "localhost:2222")
        rec = fed.get_clients()[0]
        assert rec.status == ACTIVE
        assert rec.consecutive_failures == 0 and rec.next_retry_round == 0
        assert not rec.finished

    def test_update_progress_after_disconnect_is_noop(self):
        fed = self._fed_with_client()
        fed.disconnect(1)
        # a push worker may report progress concurrently with disconnect():
        # a vanished record must be a no-op, not a KeyError
        fed.update_progress(1, 5, 1, 0.5, finished=False)
        assert len(fed) == 0


MODEL_KWARGS = dict(
    n_components=3, hidden_sizes=(8,), batch_size=8, num_epochs=2, seed=0,
)


def _server(**kw):
    base = dict(min_clients=1, family="avitm", model_kwargs=MODEL_KWARGS)
    base.update(kw)
    return FederatedServer(**base)


class TestServerUnits:
    def test_rejoin_with_new_address_gets_fresh_stub(self):
        server = _server()
        stubs = {}
        rec = ClientRecord(1, address="localhost:7001",
                           ready_for_training=True)
        first = server._stub_for(stubs, rec)
        assert first is not None
        assert server._stub_for(stubs, rec) is first  # cached while stable
        rec.address = "localhost:7002"  # rejoin on a new port
        second = server._stub_for(stubs, rec)
        assert second is not first
        assert stubs[1][0] == "localhost:7002"
        # an address-less record falls back to whatever stub exists
        rec.address = ""
        assert server._stub_for(stubs, rec) is second
        assert server._stub_for({}, ClientRecord(2)) is None

    def test_drop_resets_poll_warm_state(self):
        """A dropped client that rejoins is a fresh process that must
        re-jit — its first poll is compile-dominated again and must be
        excluded from the straggler stats."""
        server = _server(probation_rounds=1)
        server.federation.connect_vocab(1, ("a",), 1.0)
        server.federation.connect_ready(1, "localhost:7001")
        rec = server.federation.get_clients()[0]
        server._poll_warmed.add(1)
        server._note_client_failure(
            rec, "localhost:7001", 0, RuntimeError("down"), "TrainStep"
        )
        assert rec.status == DROPPED
        assert 1 not in server._poll_warmed

    def test_suspect_keeps_poll_warm_state(self):
        server = _server(probation_rounds=3)
        server.federation.connect_vocab(1, ("a",), 1.0)
        server.federation.connect_ready(1, "localhost:7001")
        rec = server.federation.get_clients()[0]
        server._poll_warmed.add(1)
        server._note_client_failure(
            rec, "localhost:7001", 0, RuntimeError("blip"), "TrainStep"
        )
        assert rec.status == SUSPECT
        assert 1 in server._poll_warmed

    def test_collect_snapshots_excludes_key_skewed_reply(self):
        m = MetricsLogger(validate=True)
        server = _server(metrics=m)
        server.template = build_template_model("avitm", 30, MODEL_KWARGS)
        tmpl = server._shared_template()
        good = pb.StepReply(client_id=1,
                            shared=codec.flatdict_to_bundle(tmpl))
        skewed_dict = dict(tmpl)
        dropped_key = sorted(skewed_dict)[0]
        skewed_dict.pop(dropped_key)
        skewed_dict["params/rogue"] = np.zeros(2, np.float32)
        skewed = pb.StepReply(client_id=2,
                              shared=codec.flatdict_to_bundle(skewed_dict))
        out = server._collect_snapshots(
            [(ClientRecord(1, nr_samples=4.0), good),
             (ClientRecord(2, nr_samples=2.0), skewed)],
            iteration=0,
        )
        assert len(out) == 1 and out[0][0] == 4.0
        assert set(out[0][1]) == set(tmpl)
        assert m.registry.counter("key_skew_excluded").value == 1

    def test_collect_snapshots_excludes_shape_skewed_reply(self):
        """Same key set over a DIFFERENT consensus vocab (the likelier
        version skew) must cost the round one contributor, not crash the
        weighted average with a broadcast error."""
        m = MetricsLogger(validate=True)
        server = _server(metrics=m)
        server.template = build_template_model("avitm", 30, MODEL_KWARGS)
        tmpl = server._shared_template()
        good = pb.StepReply(client_id=1,
                            shared=codec.flatdict_to_bundle(tmpl))
        stale = {
            k: np.zeros(v.shape + (2,), v.dtype) if k == sorted(tmpl)[0]
            else v
            for k, v in tmpl.items()
        }
        skewed = pb.StepReply(client_id=2,
                              shared=codec.flatdict_to_bundle(stale))
        out = server._collect_snapshots(
            [(ClientRecord(1, nr_samples=4.0), good),
             (ClientRecord(2, nr_samples=2.0), skewed)],
            iteration=0,
        )
        assert len(out) == 1 and out[0][0] == 4.0
        assert m.registry.counter("key_skew_excluded").value == 1

    def test_stop_joins_training_thread(self):
        server = _server()
        t = threading.Thread(target=server._stopping.wait, daemon=True)
        t.start()
        server._train_thread = t
        server.stop(grace=0, join_timeout=5.0)
        assert server._stopping.is_set()
        assert not t.is_alive()

    def test_knob_validation(self):
        with pytest.raises(ValueError):
            _server(probation_rounds=0)
        with pytest.raises(ValueError):
            _server(quorum_fraction=1.5)

    def test_restore_without_checkpoint_raises(self, tmp_path):
        server = _server(save_dir=str(tmp_path))
        with pytest.raises(FileNotFoundError):
            server.restore_from_checkpoint()
        with pytest.raises(ValueError):
            _server(save_dir=None)._checkpointer()


# ---- round checkpointing ----------------------------------------------------

class TestFederationCheckpointer:
    def test_save_restore_roundtrip(self, tmp_path):
        ckpt = FederationCheckpointer(str(tmp_path))
        rng = np.random.default_rng(0)
        avg = {"params/beta": rng.normal(size=(3, 5)).astype(np.float32),
               "params/prior_mean": rng.normal(size=3).astype(np.float32)}
        membership = [{"client_id": 1, "nr_samples": 4.0, "current_mb": 7,
                       "current_epoch": 1, "finished": False,
                       "status": "active"}]
        ckpt.save_round(6, avg, membership, vocab=["a", "b"],
                        extra={"family": "avitm"})
        assert ckpt.latest_round() == 6
        meta = ckpt.load_meta()
        assert meta["round"] == 6 and meta["vocab"] == ["a", "b"]
        assert meta["membership"] == membership
        assert meta["family"] == "avitm"

        template = {k: np.zeros_like(v) for k, v in avg.items()}
        step, restored = ckpt.restore_round(template)
        assert step == 6
        for k in avg:
            np.testing.assert_allclose(restored[k], avg[k])
        ckpt.close()

    def test_latest_checkpoint_wins(self, tmp_path):
        ckpt = FederationCheckpointer(str(tmp_path))
        avg = {"a": np.full(2, 1.0, np.float32)}
        ckpt.save_round(2, avg, [], vocab=["x"])
        ckpt.save_round(4, {"a": np.full(2, 9.0, np.float32)}, [],
                        vocab=["x"])
        step, restored = ckpt.restore_round({"a": np.zeros(2, np.float32)})
        assert step == 4
        np.testing.assert_allclose(restored["a"], 9.0)
        ckpt.close()

    def test_resave_of_latest_round_is_noop(self, tmp_path):
        """The server's final checkpoint can land on the same round as the
        last periodic one — must be a silent no-op, not an orbax
        StepAlreadyExistsError."""
        ckpt = FederationCheckpointer(str(tmp_path))
        avg = {"a": np.full(2, 1.0, np.float32)}
        ckpt.save_round(2, avg, [], vocab=["x"])
        ckpt.save_round(2, avg, [], vocab=["x"])  # duplicate round
        assert ckpt.latest_round() == 2
        ckpt.close()

    def test_template_key_mismatch_detected(self, tmp_path):
        ckpt = FederationCheckpointer(str(tmp_path))
        ckpt.save_round(1, {"a": np.zeros(2, np.float32)}, [], vocab=["x"])
        with pytest.raises(ValueError, match="model config"):
            ckpt.restore_round({"b": np.zeros(2, np.float32)})
        ckpt.close()

    def test_empty_directory(self, tmp_path):
        ckpt = FederationCheckpointer(str(tmp_path))
        assert ckpt.latest_round() is None
        assert ckpt.load_meta() is None
        with pytest.raises(FileNotFoundError):
            ckpt.restore_round({"a": np.zeros(2)})
        ckpt.close()

    def test_server_level_checkpoint_restore(self, tmp_path):
        """A fresh server process restores vocab + template + average +
        round counter from a crashed server's checkpoint directory."""
        from gfedntm_tpu.data.vocab import Vocabulary

        tokens = tuple(f"tok{i:02d}" for i in range(30))
        crashed = _server(save_dir=str(tmp_path), checkpoint_every=1)
        crashed.global_vocab = Vocabulary(tokens)
        crashed.template = build_template_model(
            "avitm", len(tokens), MODEL_KWARGS
        )
        crashed.last_average = {
            k: v + 1.0 for k, v in crashed._shared_template().items()
        }
        crashed.global_iterations = 7
        crashed._save_round_checkpoint()

        resumed = _server(save_dir=str(tmp_path))
        assert resumed.restore_from_checkpoint() == 7
        assert resumed.global_iterations == 7
        assert tuple(resumed.global_vocab.tokens) == tokens
        assert set(resumed.last_average) == set(crashed.last_average)
        for k, v in crashed.last_average.items():
            np.testing.assert_allclose(resumed.last_average[k], v)
        # the restored average was applied onto the template so rejoining
        # clients replicate the TRAINED state, not a fresh init
        assert resumed._setup_reply is not None


# ---- client liveness watchdog -----------------------------------------------

class _FakeStepper:
    def get_results_model(self, save_dir):
        return {"betas": np.zeros((1, 1), np.float32)}


def test_watchdog_self_finalizes_without_server(monkeypatch):
    """A client whose server vanished (no polls, no stop broadcast) must
    self-finalize after the liveness window instead of blocking in
    stopped.wait() forever."""
    m = MetricsLogger(validate=True)
    client = Client(
        client_id=1, corpus=RawCorpus(documents=["a b"]),
        server_address="localhost:1", metrics=m,
        liveness_timeout=0.3, watchdog_poll_s=0.05,
    )
    monkeypatch.setattr(client, "join_federation", lambda: None)
    monkeypatch.setattr(client, "serve_training", lambda: None)
    client.stepper = _FakeStepper()
    t = threading.Thread(target=client.run, daemon=True)
    t.start()
    t.join(timeout=10.0)
    assert not t.is_alive(), "watchdog never fired"
    assert client.stopped.is_set()
    assert client.results is not None
    assert m.registry.counter("watchdog_self_finalized").value == 1
    assert m.events("watchdog_fired")[0]["client"] == 1


def test_watchdog_holds_while_server_call_in_flight(monkeypatch):
    """An open TrainStep/ApplyAggregate counts as liveness for its whole
    duration: a local step legitimately running past the liveness window
    (e.g. a long E-step round) must not trigger a spurious self-finalize."""
    client = Client(
        client_id=1, corpus=RawCorpus(documents=["a b"]),
        server_address="localhost:1",
        liveness_timeout=0.2, watchdog_poll_s=0.02,
    )
    monkeypatch.setattr(client, "join_federation", lambda: None)
    monkeypatch.setattr(client, "serve_training", lambda: None)
    client.stepper = _FakeStepper()
    t = threading.Thread(target=client.run, daemon=True)
    client._rpc_begin()  # a server call dispatches, then runs "forever"
    t.start()
    time.sleep(0.6)  # 3x the liveness window
    assert t.is_alive(), "watchdog fired during an in-flight call"
    assert client.results is None
    client._rpc_end()  # the call returns; idle clock restarts from here
    t.join(timeout=10.0)
    assert not t.is_alive(), "watchdog never fired after the call ended"
    assert client.results is not None


def test_watchdog_window_scales_with_local_steps():
    """The server's poll deadline is 120 + 2E; a StepRequest revealing E
    must widen the liveness window by the same factor so a slow-but-alive
    peer's round can't look like a dead server."""
    client = Client(
        client_id=1, corpus=RawCorpus(documents=["a b"]),
        server_address="localhost:1", liveness_timeout=100.0,
    )
    client._last_activity = time.monotonic() - 200.0
    client._note_local_steps(150)  # deadline 420 s → scale 3.5, window 350
    assert client._deadline_scale == pytest.approx(3.5)
    assert client._idle_expired() is None
    client._note_local_steps(1)  # scale ~1.02, window ~102 < 200 idle
    assert client._idle_expired() == pytest.approx(200.0, abs=5.0)


def test_watchdog_disabled_with_zero_timeout(monkeypatch):
    client = Client(
        client_id=1, corpus=RawCorpus(documents=["a b"]),
        server_address="localhost:1",
        liveness_timeout=0.0, watchdog_poll_s=0.02,
    )
    monkeypatch.setattr(client, "join_federation", lambda: None)
    monkeypatch.setattr(client, "serve_training", lambda: None)
    client.stepper = _FakeStepper()
    t = threading.Thread(target=client.run, daemon=True)
    t.start()
    time.sleep(0.3)
    assert t.is_alive()  # no watchdog: still waiting on the server
    client._on_stop()  # release it
    t.join(timeout=5.0)
    assert not t.is_alive()


# ---- CLI knobs --------------------------------------------------------------

def test_parser_fault_tolerance_flags():
    p = build_parser()
    args = p.parse_args([])
    assert args.resume is False
    assert args.checkpoint_every == 25
    assert args.probation_rounds == 3
    assert args.quorum_fraction == 0.5
    assert args.liveness_timeout == 300.0
    args = p.parse_args(
        ["--resume", "--checkpoint_every", "5", "--quorum_fraction", "0.8",
         "--probation_rounds", "2", "--liveness_timeout", "60"]
    )
    assert args.resume and args.checkpoint_every == 5
    assert args.quorum_fraction == 0.8
    assert args.probation_rounds == 2 and args.liveness_timeout == 60.0


# ---- chaos: scripted faults over real gRPC ----------------------------------

def _corpora(n_clients, docs, seed=0):
    rng = np.random.default_rng(seed)
    words = [f"tok{i:02d}" for i in range(45)]
    return [
        RawCorpus(documents=[
            " ".join(rng.choice(words, size=12)) for _ in range(docs)
        ])
        for _ in range(n_clients)
    ]


@pytest.mark.chaos
def test_servicer_side_injection_surfaces_real_grpc_status():
    """An injector on the SERVICER dispatch path aborts the call with a real
    gRPC status, which the caller's RetryPolicy then recovers from."""

    class Impl:
        def OfferVocab(self, request, context):
            return pb.Ack(code=0, detail="ok")

        def GetGlobalSetup(self, request, context):
            return pb.GlobalSetup()

        def ReadyForTraining(self, request, context):
            return pb.Ack(code=0, detail="ok")

    inj = FaultInjector(seed=0)
    server = rpc.make_server(max_workers=4)
    rpc.add_service(server, "gfedntm.Federation", Impl(), fault_injector=inj)
    port = server.add_insecure_port("[::]:0")
    server.start()
    try:
        channel = rpc.make_channel(f"localhost:{port}")
        plain = rpc.ServiceStub(channel, "gfedntm.Federation",
                                default_timeout=10.0)
        inj.script("OfferVocab", times=1)
        with pytest.raises(grpc.RpcError) as err:
            plain.OfferVocab(pb.VocabOffer(client_id=1))
        assert err.value.code() is UNAVAILABLE
        assert plain.OfferVocab(pb.VocabOffer(client_id=1)).code == 0

        # with a retry policy the same scripted blip is invisible
        retrying = rpc.ServiceStub(
            rpc.make_channel(f"localhost:{port}"), "gfedntm.Federation",
            default_timeout=10.0,
            retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.01,
                                     max_delay_s=0.02, seed=0),
        )
        inj.script("OfferVocab", times=1)
        assert retrying.OfferVocab(pb.VocabOffer(client_id=1)).code == 0
        assert len(inj.fired) == 2
    finally:
        server.stop(0)


@pytest.mark.chaos
def test_transient_trainstep_faults_recover_with_all_clients(tmp_path):
    """Acceptance scenario: a 3-client federation where client 1's TrainStep
    fails transiently for 2 consecutive rounds (in-call retries exhausted
    both rounds) completes with all 3 clients fully trained, the suspect
    recovering via probation, and the retry/recovery counters visible in
    the metrics snapshot."""
    path = str(tmp_path / "metrics.jsonl")
    metrics = MetricsLogger(path, validate=True)
    inj = FaultInjector(seed=0, metrics=metrics)
    # 5 scripted UNAVAILABLEs against client1 with a 2-attempt retry budget:
    # round r consumes 2 (failed round #1), round r+1 consumes 2 (failed
    # round #2, backoff pushes the re-poll 2 rounds out), the re-poll round
    # consumes 1 then succeeds on the in-call retry (a retry_success).
    inj.script("TrainStep", times=5, peer="client1")
    server = FederatedServer(
        min_clients=3, family="avitm", model_kwargs=MODEL_KWARGS,
        max_iters=60, save_dir=str(tmp_path / "server"), metrics=metrics,
        retry_policy=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                                 max_delay_s=0.05, seed=1, metrics=metrics),
        probation_rounds=3, fault_injector=inj, checkpoint_every=0,
    )
    addr = server.start("[::]:0")
    clients = [
        Client(client_id=c + 1, corpus=corpus, server_address=addr,
               max_features=45, save_dir=str(tmp_path / f"client{c + 1}"),
               metrics=metrics)
        for c, corpus in enumerate(_corpora(3, docs=40))
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    try:
        assert server.wait_done(timeout=600), "federation did not finish"
        for t in threads:
            t.join(timeout=60)
    finally:
        server.stop()
        for c in clients:
            c.shutdown()
        metrics.close()

    # every scripted fault fired against client1's stub, nobody else's
    assert [f[:2] for f in inj.fired] == [("TrainStep", "client1")] * 5

    # all 3 clients trained to completion and produced artifacts — the
    # faulted client contributed again after recovery
    for c in clients:
        assert c.stopped.is_set() and c.results is not None
        assert c.stepper.current_epoch == MODEL_KWARGS["num_epochs"]
        assert c.stepper.finished
    recs = {r.client_id: r for r in server.federation.get_clients()}
    assert recs[1].status == ACTIVE  # recovered, never dropped
    # the faulted client's recorded progress matches its healthy peers'
    # (reply.current_mb lags the stepper by the final step's accounting,
    # which lands in the push — identical for all three)
    assert recs[1].current_mb == recs[2].current_mb == recs[3].current_mb > 0

    reg = metrics.registry
    assert reg.counter("client_suspect_rounds").value == 2
    assert reg.counter("client_recoveries").value == 1
    assert reg.counter("client_drops").value == 0
    assert reg.counter("retry_attempts").value == 3
    assert reg.counter("retry_giveups").value == 2
    assert reg.counter("retry_successes").value == 1
    assert reg.counter("faults_injected").value == 5

    # ... and the same counters are visible in the persisted snapshot
    records = read_metrics(path)
    merged = {}
    for r in records:
        if r["event"] == "metrics_snapshot":
            merged.update(r["metrics"])
    assert merged["client_recoveries"]["value"] == 1
    assert merged["client_suspect_rounds"]["value"] == 2
    assert merged["retry_attempts"]["value"] == 3
    suspects = [r for r in records if r["event"] == "client_suspect"]
    recoveries = [r for r in records if r["event"] == "client_recovered"]
    assert len(suspects) == 2 and len(recoveries) == 1
    assert all(s["client"] == 1 for s in suspects + recoveries)


@pytest.mark.chaos
def test_below_quorum_rounds_are_skipped_not_averaged(tmp_path):
    """quorum_fraction=1.0 with one client failing: the two failed rounds
    AND the backoff round where only the healthy client is pollable are
    SKIPPED (no average from the lone straggler's parameters — the quorum
    denominator is the full unfinished membership, suspects included),
    then the suspect recovers and the run completes."""
    metrics = MetricsLogger(validate=True)
    inj = FaultInjector(seed=0)
    inj.script("TrainStep", times=2, peer="client1")
    kwargs = dict(MODEL_KWARGS, num_epochs=1)
    server = FederatedServer(
        min_clients=2, family="avitm", model_kwargs=kwargs,
        max_iters=40, save_dir=str(tmp_path / "server"), metrics=metrics,
        retry_policy=RetryPolicy(max_attempts=1, metrics=metrics),
        quorum_fraction=1.0, probation_rounds=3, fault_injector=inj,
        round_backoff_s=0.05, checkpoint_every=0,
    )
    addr = server.start("[::]:0")
    clients = [
        Client(client_id=c + 1, corpus=corpus, server_address=addr,
               max_features=45, save_dir=str(tmp_path / f"client{c + 1}"))
        for c, corpus in enumerate(_corpora(2, docs=40, seed=1))
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    try:
        assert server.wait_done(timeout=600), "federation did not finish"
        for t in threads:
            t.join(timeout=60)
    finally:
        server.stop()
        for c in clients:
            c.shutdown()

    for c in clients:
        assert c.stopped.is_set() and c.stepper.finished
    reg = metrics.registry
    # 2 failed rounds + the backoff round where the suspect was not
    # pollable but still counted in the quorum denominator
    assert reg.counter("quorum_skipped_rounds").value == 3
    assert reg.counter("client_suspect_rounds").value == 2
    assert reg.counter("client_recoveries").value == 1
    assert reg.counter("client_drops").value == 0
    skips = metrics.events("quorum_skip")
    assert len(skips) == 3
    assert all(s["got"] == 1 and s["needed"] == 2 for s in skips)


@pytest.mark.chaos
def test_all_suspect_backoff_repolls_early_without_burning_rounds(tmp_path):
    """When EVERY pollable client is inside its probation backoff window,
    the round clock cannot advance, so the server converts the gap to the
    earliest scheduled retry into wall-clock waiting and re-polls early —
    it must not burn one max_iters round per backoff tick."""
    metrics = MetricsLogger(validate=True)
    inj = FaultInjector(seed=0)
    inj.script("TrainStep", times=2, peer="client1")
    server = FederatedServer(
        min_clients=1, family="avitm",
        model_kwargs=dict(MODEL_KWARGS, num_epochs=1),
        max_iters=40, save_dir=str(tmp_path / "server"), metrics=metrics,
        retry_policy=RetryPolicy(max_attempts=1, metrics=metrics),
        probation_rounds=3, fault_injector=inj,
        round_backoff_s=0.05, checkpoint_every=0,
    )
    addr = server.start("[::]:0")
    client = Client(client_id=1, corpus=_corpora(1, docs=40)[0],
                    server_address=addr, max_features=45,
                    save_dir=str(tmp_path / "client1"))
    t = threading.Thread(target=client.run, daemon=True)
    t.start()
    try:
        assert server.wait_done(timeout=600), "federation did not finish"
        t.join(timeout=60)
    finally:
        server.stop()
        client.shutdown()

    assert client.stepper.finished
    reg = metrics.registry
    assert reg.counter("client_suspect_rounds").value == 2
    assert reg.counter("client_recoveries").value == 1
    # Failures at rounds 0 and 1 push next_retry_round to 3; rounds 0/1
    # execute (and fail), then the all-suspect window is waited out in
    # wall-clock and the re-poll lands at round 2 — round index 2, not 3,
    # proves the backoff wait did not consume a round of the budget.
    assert metrics.events("client_recovered")[0]["round"] == 2


@pytest.mark.chaos
def test_server_crash_checkpoint_resume(tmp_path):
    """Acceptance scenario (legacy recovery path — journal and session
    reconnect disabled, see tests/test_survival.py for the survivable
    flow): a hard-killed server's round state survives via the periodic
    checkpoint; abandoned clients self-finalize on their liveness
    watchdogs; a fresh server process resumes from the checkpointed
    round (NOT round 0) and rejoining clients train to completion."""
    metrics1 = MetricsLogger(str(tmp_path / "run1.jsonl"), validate=True)
    server1 = FederatedServer(
        min_clients=2, family="avitm", model_kwargs=MODEL_KWARGS,
        max_iters=60, save_dir=str(tmp_path / "server"), metrics=metrics1,
        checkpoint_every=2, journal_every=0,
    )
    addr1 = server1.start("[::]:0")
    gen1 = [
        Client(client_id=c + 1, corpus=corpus, server_address=addr1,
               max_features=45, save_dir=str(tmp_path / f"g1c{c + 1}"),
               metrics=metrics1, liveness_timeout=120.0,
               watchdog_poll_s=0.1, reconnect_window=0.0)
        for c, corpus in enumerate(_corpora(2, docs=40, seed=2))
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in gen1]
    for t in threads:
        t.start()

    # let it train past the first periodic checkpoint (rounds 0..2 done)
    deadline = time.time() + 300
    while time.time() < deadline and server1.global_iterations < 3:
        time.sleep(0.1)
    assert server1.global_iterations >= 3, "training never reached round 3"
    server1.abort()  # SIGKILL-equivalent: no stop broadcast, no finalize

    # the abandoned clients' watchdogs fire once their window elapses
    for c in gen1:
        c.liveness_timeout = 0.5
    for t in threads:
        t.join(timeout=60)
    metrics1.close()
    for c in gen1:
        assert c.stopped.is_set(), "watchdog never released the client"
        assert c.results is not None  # self-finalized artifacts
        c.shutdown()
    assert metrics1.registry.counter("watchdog_self_finalized").value == 2

    # a fresh server process resumes from the checkpointed round
    metrics2 = MetricsLogger(str(tmp_path / "run2.jsonl"), validate=True)
    server2 = FederatedServer(
        min_clients=2, family="avitm", model_kwargs=MODEL_KWARGS,
        max_iters=60, save_dir=str(tmp_path / "server"), metrics=metrics2,
        checkpoint_every=2, journal_every=0,
    )
    resumed_round = server2.restore_from_checkpoint()
    assert resumed_round >= 2 and resumed_round % 2 == 0
    assert server2.global_iterations == resumed_round
    assert set(server2.last_average) == set(server1.last_average)

    addr2 = server2.start("[::]:0")
    gen2 = [
        Client(client_id=c + 1, corpus=corpus, server_address=addr2,
               max_features=45, save_dir=str(tmp_path / f"g2c{c + 1}"),
               metrics=metrics2)
        for c, corpus in enumerate(_corpora(2, docs=40, seed=2))
    ]
    threads2 = [threading.Thread(target=c.run, daemon=True) for c in gen2]
    for t in threads2:
        t.start()
    try:
        assert server2.wait_done(timeout=600), "resumed run did not finish"
        for t in threads2:
            t.join(timeout=60)
    finally:
        server2.stop()
        for c in gen2:
            c.shutdown()
        metrics2.close()

    for c in gen2:
        assert c.stopped.is_set() and c.results is not None
        assert c.stepper.finished
    assert server2.global_iterations > resumed_round
    assert np.isfinite(server2.global_betas).all()

    # the resumed run's telemetry proves it never revisited round 0: the
    # resume event carries the checkpointed round and every round span of
    # run 2 is at or beyond it
    records = read_metrics(str(tmp_path / "run2.jsonl"))
    resumes = [r for r in records if r["event"] == "resume"]
    assert resumes and resumes[0]["step"] == resumed_round
    round_spans = [r for r in records
                   if r["event"] == "span" and r["name"] == "round"]
    assert round_spans
    assert min(s["round"] for s in round_spans) == resumed_round
