"""BASELINE.json preset smoke tests at tiny scale (the five evaluation
configs any reproduction must cover)."""

import numpy as np
import pytest

from gfedntm_tpu import presets


def test_registry_covers_the_five_baseline_configs():
    assert set(presets.PRESETS) == {
        "prodlda_1client_synthetic",
        "neurallda_2client_iid",
        "prodlda_5client_20ng",
        "combinedtm_5client",
        "noniid_fos_5client",
        # beyond-baseline: the offline real-text federation
        "realtext_docstrings_5client",
    }


@pytest.mark.slow
def test_realtext_docstrings_preset_smoke():
    """Tiny-scale end-to-end: extraction -> consensus -> federated fit ->
    real-word topics (the always-available real-text preset)."""
    res = presets.realtext_docstrings_5client(
        scale=0.02, n_components=5, local_steps=2
    )
    assert res.summary["n_clients"] == 5
    assert np.isfinite(res.summary["final_mean_loss"])
    m = res.summary["metrics"]
    assert -1.0 <= m["npmi"] <= 1.0
    topics = res.extras["topics"]
    assert len(topics) == 5
    assert all(not w.isdigit() for t in topics for w in t)


@pytest.mark.slow
def test_prodlda_1client_synthetic():
    res = presets.prodlda_1client_synthetic(scale=0.02)
    assert res.summary["n_clients"] == 1
    assert np.isfinite(res.summary["final_mean_loss"])
    gt = res.extras["ground_truth"]
    assert gt.topic_vectors.shape[0] == 10


@pytest.mark.slow
def test_neurallda_2client_iid():
    res = presets.neurallda_2client_iid(scale=0.02)
    assert res.summary["n_clients"] == 2
    assert np.isfinite(res.summary["final_mean_loss"])
    # NeuralLDA: the trained template family must be LDA
    assert res.trainer.template.model_type == "LDA"


@pytest.mark.slow
def test_combinedtm_5client():
    res = presets.combinedtm_5client(scale=0.02)
    assert res.summary["n_clients"] == 5
    assert np.isfinite(res.summary["final_mean_loss"])
    assert res.trainer.template.inference_type == "combined"


def test_20ng_preset_raises_cleanly_without_cache(tmp_path):
    with pytest.raises(OSError):
        presets.prodlda_5client_20ng(scale=0.01, data_home=str(tmp_path))


def test_noniid_preset_validates_categories():
    with pytest.raises(ValueError, match="5 categories"):
        presets.noniid_fos_5client("/nonexistent.parquet", ["a", "b"])


def test_noniid_preset_raises_cleanly_without_data():
    with pytest.raises(FileNotFoundError, match="never downloads"):
        presets.noniid_fos_5client("/nonexistent.parquet")


_HAS_S2CS = __import__("os").path.exists(presets.S2CS_TINY_PARQUET)


@pytest.mark.skipif(not _HAS_S2CS, reason="reference s2cs_tiny fixture absent")
@pytest.mark.slow
def test_noniid_fos_5client_real_corpus_end_to_end():
    """The full config-5 path on the reference's real-corpus fixture:
    FOS partition -> vocabulary consensus -> SPMD federated fit ->
    NPMI/diversity/RBO on the aggregated global model."""
    res = presets.noniid_fos_5client(scale=0.3, n_components=10)
    assert res.summary["n_clients"] == 5
    assert len(res.summary["fos_categories"]) == 5
    assert np.isfinite(res.summary["final_mean_loss"])
    m = res.summary["metrics"]
    assert -1.0 <= m["npmi"] <= 1.0
    assert 0.0 < m["topic_diversity"] <= 1.0
    assert 0.0 <= m["inverted_rbo"] <= 1.0
    topics = res.extras["topics"]
    assert len(topics) == 10 and all(len(t) == 10 for t in topics)
    # topics are real corpus words, not ids
    vocab_words = {w for t in topics for w in t}
    assert all(not w.isdigit() for w in vocab_words)


def test_hashing_embedder_deterministic_unit_norm():
    embed = presets.hashing_embedder(32)
    e1 = embed(["hello world", "foo bar baz"])
    e2 = embed(["hello world", "foo bar baz"])
    np.testing.assert_array_equal(e1, e2)
    norms = np.linalg.norm(e1, axis=1)
    np.testing.assert_allclose(norms, 1.0, rtol=1e-5)
