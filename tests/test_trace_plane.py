"""Cross-process trace plane tests (tier-1): gRPC trace-metadata
propagation (stub → servicer roundtrip over a real in-process channel,
missing-metadata tolerance), span trace-id inheritance, the clock-aligning
Chrome-trace merger on golden two-node logs with skewed clocks, and a
3-client end-to-end federation whose per-node JSONL streams merge into one
trace where every round span has child spans from all clients sharing the
server's trace_id — with the live ops endpoint curled mid-run."""

import json
import re
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from gfedntm_tpu.federation import rpc
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.utils.observability import (
    NODE_KEY,
    PARENT_SPAN_KEY,
    ROUND_KEY,
    SEND_TIME_KEY,
    TRACE_ID_KEY,
    TRACE_PLANE_SPANS,
    MetricsLogger,
    ambient_trace_pairs,
    estimate_clock_offset,
    extract_trace_context,
    merge_chrome_trace,
    new_trace_id,
    read_metrics,
    span,
    trace_pairs,
    validate_record,
)


# ---- metadata helpers -------------------------------------------------------

class TestTraceContextHelpers:
    def test_pairs_roundtrip_through_extract(self):
        pairs = trace_pairs("abc123", 42, 7)
        pairs += [(NODE_KEY, "client2"), (SEND_TIME_KEY, "12.5")]
        ctx = extract_trace_context(pairs)
        assert ctx == {
            "trace_id": "abc123", "remote_parent_id": 42, "round": 7,
            "remote_node": "client2", "rpc_send_time": 12.5,
        }

    def test_extract_tolerates_missing_and_malformed(self):
        assert extract_trace_context(None) == {}
        assert extract_trace_context(()) == {}
        # malformed values are dropped, valid siblings survive
        ctx = extract_trace_context([
            (PARENT_SPAN_KEY, "not-an-int"),
            (ROUND_KEY, "3"),
            (SEND_TIME_KEY, "junk"),
            ("some-unrelated-key", "x"),
        ])
        assert ctx == {"round": 3}

    def test_new_trace_ids_are_unique_hex(self):
        ids = {new_trace_id() for _ in range(32)}
        assert len(ids) == 32
        assert all(re.fullmatch(r"[0-9a-f]{16}", t) for t in ids)

    def test_span_inherits_trace_id_from_logger_and_parent(self):
        log = MetricsLogger(validate=True)
        log.trace_id = "t-log"
        with span(log, "round") as outer:
            assert outer.fields["trace_id"] == "t-log"
            with span(log, "poll") as inner:
                pass
        events = {e["name"]: e for e in log.events("span")}
        assert events["round"]["trace_id"] == "t-log"
        assert events["poll"]["trace_id"] == "t-log"
        assert inner.parent_id == outer.span_id
        # explicit trace_id wins over the logger's
        with span(log, "serve", trace_id="t-remote"):
            pass
        assert log.events("span")[-1]["trace_id"] == "t-remote"

    def test_ambient_pairs_reflect_open_span(self):
        log = MetricsLogger()
        log.trace_id = "amb"
        with span(log, "outer") as sp:
            pairs = dict(ambient_trace_pairs(log))
            assert pairs[TRACE_ID_KEY] == "amb"
            assert pairs[PARENT_SPAN_KEY] == str(sp.span_id)
        # no open span: trace id only
        assert dict(ambient_trace_pairs(log)) == {TRACE_ID_KEY: "amb"}
        # nothing at all: empty (and therefore no metadata)
        assert ambient_trace_pairs(MetricsLogger()) == []

    def test_trace_plane_span_names_are_the_documented_set(self):
        assert set(TRACE_PLANE_SPANS) == {
            "round", "serve", "relay_fanout", "relay_push", "infer",
            "serve_batch", "serve_swap",
        }


# ---- stub -> servicer roundtrip over a real channel -------------------------

class _FederationImpl:
    """Minimal gfedntm.Federation servicer for metadata tests."""

    def OfferVocab(self, request, context):
        return pb.Ack(code=0, detail="ok")

    def GetGlobalSetup(self, request, context):  # pragma: no cover
        raise NotImplementedError

    def ReadyForTraining(self, request, context):
        return pb.Ack(code=0, detail="ok")


@pytest.fixture()
def federation_pair():
    """(server_metrics, client_metrics, stub) over a live in-process gRPC
    server with the traced dispatch installed."""
    server_metrics = MetricsLogger(validate=True, node="server")
    client_metrics = MetricsLogger(validate=True, node="client1")
    grpc_server = rpc.make_server(max_workers=2)
    rpc.add_service(
        grpc_server, "gfedntm.Federation", _FederationImpl(),
        metrics=server_metrics,
    )
    port = grpc_server.add_insecure_port("[::]:0")
    grpc_server.start()
    channel = rpc.make_channel(f"localhost:{port}")
    stub = rpc.ServiceStub(
        channel, "gfedntm.Federation", metrics=client_metrics, peer="server",
    )
    yield server_metrics, client_metrics, stub
    channel.close()
    grpc_server.stop(0)


class TestMetadataPropagation:
    def test_ambient_span_context_reaches_servicer(self, federation_pair):
        server_metrics, client_metrics, stub = federation_pair
        client_metrics.trace_id = "roundtrip01"
        with span(client_metrics, "join", client=1) as sp:
            stub.OfferVocab(
                pb.VocabOffer(client_id=1, tokens=["a"], nr_samples=1.0)
            )
        (serve,) = server_metrics.events("span")
        assert serve["name"] == "serve"
        assert serve["method"] == "Federation.OfferVocab"
        assert serve["trace_id"] == "roundtrip01"
        assert serve["remote_parent_id"] == sp.span_id
        assert serve["remote_node"] == "client1"
        assert serve["client"] == 1
        # same host, same clock: the paired stamps bracket the dispatch
        assert serve["rpc_send_time"] <= serve["rpc_recv_time"]
        assert serve["node"] == "server"

    def test_explicit_metadata_overrides_ambient(self, federation_pair):
        server_metrics, client_metrics, stub = federation_pair
        client_metrics.trace_id = "ambient-loses"
        stub.ReadyForTraining(
            pb.JoinRequest(client_id=2),
            metadata=trace_pairs("explicit-wins", 99, 5),
        )
        (serve,) = server_metrics.events("span")
        assert serve["trace_id"] == "explicit-wins"
        assert serve["remote_parent_id"] == 99
        assert serve["round"] == 5

    def test_missing_metadata_tolerated(self):
        """A metrics=None stub attaches no metadata; the servicer-side
        serve span still logs, with no trace fields."""
        server_metrics = MetricsLogger(validate=True, node="server")
        grpc_server = rpc.make_server(max_workers=2)
        rpc.add_service(
            grpc_server, "gfedntm.Federation", _FederationImpl(),
            metrics=server_metrics,
        )
        port = grpc_server.add_insecure_port("[::]:0")
        grpc_server.start()
        channel = rpc.make_channel(f"localhost:{port}")
        try:
            stub = rpc.ServiceStub(channel, "gfedntm.Federation")
            stub.OfferVocab(
                pb.VocabOffer(client_id=3, tokens=["b"], nr_samples=2.0)
            )
            (serve,) = server_metrics.events("span")
            assert serve["name"] == "serve"
            assert "trace_id" not in serve
            assert "remote_node" not in serve
            assert "rpc_send_time" not in serve
            assert serve["client"] == 3
        finally:
            channel.close()
            grpc_server.stop(0)


# ---- golden trace merge with skewed clocks ----------------------------------

def _span(name, span_id, t_end, seconds, **fields):
    r = {
        "event": "span", "name": name, "span_id": span_id,
        "parent_id": fields.pop("parent_id", None), "seconds": seconds,
        "time": t_end, "ok": True, "thread": fields.pop("thread", 1),
        **fields,
    }
    validate_record(r)
    return r


#: The golden scenario: client1's wall clock runs exactly +5 s ahead of the
#: server's; true one-way network latency is 10 ms in both directions.
_SKEW, _LAT = 5.0, 0.01


def _golden_nodes():
    t = 1_700_000_000.0  # server-true epoch origin
    server = [
        # reverse-direction pairing: client -> server join RPC
        _span("serve", 50, t + 1.0, 0.2, method="Federation.OfferVocab",
              remote_node="client1", client=1,
              rpc_send_time=(t + 0.8) + _SKEW,          # client clock
              rpc_recv_time=(t + 0.8) + _LAT),          # server clock
        # the round root
        _span("round", 101, t + 21.0, 1.0, round=0, trace_id="tg1"),
    ]
    # forward pairing: the server's round-0 poll dispatched at t+20.0
    poll_recv_true = t + 20.0 + _LAT
    client = [
        _span("serve", 7, poll_recv_true + _SKEW + 0.1, 0.1,
              method="FederationClient.TrainStep", trace_id="tg1",
              remote_node="server", remote_parent_id=101, round=0,
              rpc_send_time=t + 20.0,                   # server clock
              rpc_recv_time=poll_recv_true + _SKEW),    # client clock
    ]
    return {"server": server, "client1": client}


class TestTraceMerge:
    def test_offset_estimate_recovers_skew(self):
        nodes = _golden_nodes()
        off = estimate_clock_offset(
            nodes["client1"], nodes["server"], "client1", "server"
        )
        # both directions available: latency floors cancel exactly
        assert off == pytest.approx(_SKEW, abs=1e-6)

    def test_offset_single_direction_degrades_to_bound(self):
        nodes = _golden_nodes()
        off = estimate_clock_offset(nodes["client1"], [], "client1", "server")
        assert off == pytest.approx(_SKEW + _LAT, abs=1e-6)
        off = estimate_clock_offset([], nodes["server"], "client1", "server")
        assert off == pytest.approx(_SKEW - _LAT, abs=1e-6)
        assert estimate_clock_offset([], [], "a", "b") == 0.0

    def test_merged_trace_aligns_clocks_and_links_round_tree(self):
        trace = merge_chrome_trace(_golden_nodes())
        meta = trace["otherData"]
        assert meta["reference"] == "server"  # owns the round spans
        assert meta["clock_offsets_s"]["client1"] == pytest.approx(
            _SKEW, abs=1e-6
        )

        names = {
            e["args"]["name"]: e["pid"]
            for e in trace["traceEvents"] if e["ph"] == "M"
        }
        assert set(names) == {"server", "client1"}
        slices = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        by = {(e["pid"], e["name"], e["args"].get("span_id")): e
              for e in slices}
        rnd = by[(names["server"], "round", 101)]
        child = by[(names["client1"], "serve", 7)]
        # aligned: the client's TrainStep slice starts one latency after
        # the poll left the server, well inside the round span — a raw
        # (unaligned) merge would put it 5 s out.
        assert child["ts"] - rnd["ts"] == pytest.approx(
            _LAT * 1e6, abs=2e3
        )
        assert rnd["ts"] <= child["ts"] <= rnd["ts"] + rnd["dur"]
        assert child["args"]["trace_id"] == rnd["args"]["trace_id"] == "tg1"

        # the cross-process parent link renders as a flow arrow pair
        flows = [e for e in trace["traceEvents"] if e["ph"] in ("s", "f")]
        assert {e["ph"] for e in flows} == {"s", "f"}
        start = next(e for e in flows if e["ph"] == "s")
        finish = next(e for e in flows if e["ph"] == "f")
        assert start["pid"] == names["server"]
        assert finish["pid"] == names["client1"]
        assert start["id"] == finish["id"]

    def test_merge_rejects_unknown_reference_and_empty(self):
        with pytest.raises(ValueError, match="reference node"):
            merge_chrome_trace(_golden_nodes(), reference="nope")
        with pytest.raises(ValueError, match="no node records"):
            merge_chrome_trace({})


# ---- 3-client end-to-end: per-node streams -> one round tree ----------------

def _tiny_corpora(n_clients, docs=10, seed=0):
    rng = np.random.default_rng(seed)
    words = [f"tok{i:02d}" for i in range(40)]
    from gfedntm_tpu.data.loaders import RawCorpus

    return [
        RawCorpus(documents=[
            " ".join(rng.choice(words, size=12)) for _ in range(docs)
        ])
        for _ in range(n_clients)
    ]


_PROM_LINE = re.compile(
    r"^(#.*|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+)$"
)


class TestThreeClientE2E:
    def test_per_node_streams_merge_into_one_round_tree(self, tmp_path):
        """The acceptance scenario: a 3-client in-process federation with
        per-node JSONL loggers produces streams the `trace` CLI merges into
        one Chrome trace where every round span has child serve spans from
        all 3 clients sharing its trace_id; the live ops endpoint serves
        Prometheus-parsable /metrics and a /status reporting the round and
        membership during the same run."""
        from gfedntm_tpu.cli import main as cli_main
        from gfedntm_tpu.federation.client import Client
        from gfedntm_tpu.federation.server import FederatedServer

        n = 3
        paths = {
            "server": str(tmp_path / "server" / "metrics.jsonl"),
            **{
                f"client{c + 1}": str(
                    tmp_path / f"client{c + 1}" / "metrics.jsonl"
                )
                for c in range(n)
            },
        }
        loggers = {
            node: MetricsLogger(path, validate=True, node=node)
            for node, path in paths.items()
        }
        model_kwargs = dict(
            n_components=3, hidden_sizes=(8,), batch_size=8, num_epochs=1,
            seed=0,
        )
        server = FederatedServer(
            min_clients=n, family="avitm", model_kwargs=model_kwargs,
            max_iters=50, save_dir=str(tmp_path / "server"),
            metrics=loggers["server"], ops_port=0,
        )
        addr = server.start("[::]:0")
        assert server.ops_actual_port
        base = f"http://127.0.0.1:{server.ops_actual_port}"

        clients = [
            Client(
                client_id=c + 1, corpus=corpus, server_address=addr,
                max_features=40, save_dir=str(tmp_path / f"client{c + 1}"),
                metrics=loggers[f"client{c + 1}"],
            )
            for c, corpus in enumerate(_tiny_corpora(n))
        ]
        threads = [
            threading.Thread(target=c.run, daemon=True) for c in clients
        ]
        for t in threads:
            t.start()

        # the ops endpoint is live from start(), before training completes
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            assert resp.status == 200 and resp.read() == b"ok\n"

        assert server.wait_done(timeout=300.0)
        for t in threads:
            t.join(timeout=60.0)

        # --- live ops endpoint, while the server is still up ---
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            assert resp.status == 200
            metrics_text = resp.read().decode()
        for line in metrics_text.strip().splitlines():
            assert _PROM_LINE.match(line), f"bad Prometheus line: {line!r}"
        assert "gfedntm_rpc_calls_total" in metrics_text
        assert "gfedntm_client_poll_s_bucket" in metrics_text
        assert "gfedntm_client_step_ewma_s" in metrics_text

        with urllib.request.urlopen(base + "/status", timeout=10) as resp:
            status = json.loads(resp.read())
        assert status["round"] == server.global_iterations >= 1
        assert status["training_done"] is True
        assert status["codec"] == "none"
        assert status["trace_id"] == server.trace_id
        # default /status carries the bounded membership SUMMARY
        # (ISSUE 11); the per-client roster moved behind ?full=1
        assert status["clients"]["total"] == n
        assert status["clients"]["by_status"] == {"active": n}
        with urllib.request.urlopen(
            base + "/status?full=1", timeout=10
        ) as resp:
            full = json.loads(resp.read())
        assert len(full["clients"]) == n
        assert {c["client_id"] for c in full["clients"]} == {1, 2, 3}
        assert all(c["status"] == "active" for c in full["clients"])

        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(base + "/nope", timeout=10)
        assert err.value.code == 404

        for c in clients:
            c.shutdown()
        server.stop()
        for logger in loggers.values():
            logger.close()

        # --- per-node JSONL: every client's serve spans share the trace ---
        trace_id = server.trace_id
        assert trace_id
        streams = {node: read_metrics(path) for node, path in paths.items()}
        for node, records in streams.items():
            for r in records:
                validate_record(r)
                assert r["node"] == node
        assert any(
            r["event"] == "trace_started" and r["trace_id"] == trace_id
            for r in streams["server"]
        )
        for c in range(1, n + 1):
            serve = [
                r for r in streams[f"client{c}"]
                if r["event"] == "span" and r["name"] == "serve"
                and r.get("trace_id") == trace_id
            ]
            assert serve, f"client{c} has no spans in trace {trace_id}"
            assert any(isinstance(r.get("round"), int) for r in serve)

        # --- the trace CLI merges them into one tree ---
        out = str(tmp_path / "trace.json")
        rc = cli_main(["trace", *paths.values(), "-o", out])
        assert rc == 0
        with open(out) as fh:
            trace = json.load(fh)
        assert trace["otherData"]["reference"] == "server"
        pid_names = {
            e["pid"]: e["args"]["name"]
            for e in trace["traceEvents"] if e["ph"] == "M"
        }
        assert set(pid_names.values()) == set(paths)
        slices = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        rounds = [
            e for e in slices
            if e["name"] == "round" and pid_names[e["pid"]] == "server"
        ]
        assert rounds and all(
            e["args"]["trace_id"] == trace_id for e in rounds
        )
        for rnd in rounds:
            children = {
                pid_names[e["pid"]]
                for e in slices
                if e["name"] == "serve"
                and e["args"].get("trace_id") == trace_id
                and e["args"].get("round") == rnd["args"]["round"]
                and pid_names[e["pid"]] != "server"
            }
            assert children == {f"client{c}" for c in range(1, n + 1)}, (
                f"round {rnd['args']['round']} missing client children: "
                f"{children}"
            )
        # cross-process links materialized as flow arrows
        assert any(e["ph"] == "s" for e in trace["traceEvents"])
        assert any(e["ph"] == "f" for e in trace["traceEvents"])
