"""1-client federation ≡ centralized training (SURVEY.md §7.2 step 4).

Two levels of equivalence are pinned:

1. **Exact:** a 1-client :class:`FederatedTrainer` run — the full SPMD
   machinery (shard_map over the client mesh, vmapped client block,
   degenerate weighted ``psum``, padding/masking) — reproduces a plain
   centralized loop driven by :func:`grad_step` with the *same* batch
   schedule and RNG folding. The federation adds nothing for one client.

2. **Documented divergence vs** :meth:`AVITM.fit`: bitwise equality with the
   centralized ``fit`` loop is intentionally NOT possible because the RNG
   streams differ by design —
   - ``fit`` draws a fresh key per epoch via ``_next_rng()`` (sequential
     ``jax.random.split``) and folds it by the *in-epoch* step index
     (``train/steps.py: build_train_epoch``), with epoch schedules from the
     model's own numpy Generator;
   - the federated program folds ONE run key by the *absolute* step index
     and the client id (resume-stable RNG, ``federated/trainer.py``), with
     schedules from ``make_run_schedule(seed*1000+c)``.
   Same generative procedure, different streams. The test asserts the
   trajectories agree in value (same data, same init, same step count) to a
   loose tolerance while the exact test above carries the real guarantee.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.traverse_util import flatten_dict

from gfedntm_tpu.data.datasets import BowDataset, make_run_schedule
from gfedntm_tpu.federated.trainer import FederatedTrainer
from gfedntm_tpu.models.avitm import AVITM
from gfedntm_tpu.train.steps import build_train_step

# The inference net's f_mu/f_sigma heads feed affine-free BatchNorm, which
# subtracts the batch mean — so their *biases* are loss-invariant directions
# whose true gradient is exactly zero. Adam divides float-rounding noise by
# float-rounding noise there, producing O(1) updates along directions that
# cannot affect any output. Two numerically-identical trajectories therefore
# agree on every leaf except these two, and on every loss bit-for-bit. (The
# reference has the same free parameters: Linear->BatchNorm1d(affine=False),
# inference_network.py:62-85.)
_BN_FREE_LEAVES = {"inf_net/f_mu/bias", "inf_net/f_sigma/bias"}


def _make_dataset(docs=52, vocab=60, seed=3):
    rng = np.random.default_rng(seed)
    return BowDataset(
        X=rng.integers(0, 4, size=(docs, vocab)).astype(np.float32),
        idx2token={i: f"wd{i}" for i in range(vocab)},
    )


def _make_model(vocab, epochs, seed=0):
    return AVITM(
        input_size=vocab, n_components=4, hidden_sizes=(16, 16),
        batch_size=8, num_epochs=epochs, lr=2e-3, momentum=0.99, seed=seed,
    )


@pytest.mark.slow
def test_one_client_federation_equals_centralized_loop():
    """The SPMD program at C=1 ≡ sequential grad_step with the same
    schedule + RNG stream: per-step losses and final params match."""
    seed, epochs = 0, 2
    d = _make_dataset()
    template = _make_model(d.vocab_size, epochs, seed=seed)
    trainer = FederatedTrainer(template, n_clients=1, seed=seed)
    result = trainer.fit([d])

    # Manual centralized loop with the trainer's schedule and RNG folding.
    model = _make_model(d.vocab_size, epochs, seed=seed)  # same init
    step_fn = build_train_step(
        model.module, model.tx, model.family, model._beta_weight()
    )
    steps = result.losses.shape[0]
    sched = make_run_schedule(len(d), model.batch_size, steps, seed=seed * 1000)
    data = {"x_bow": jnp.asarray(d.X)}
    run_key = jax.random.PRNGKey(seed + 17)
    params, batch_stats, opt_state = model.params, model.batch_stats, model.opt_state
    manual_losses = []
    for i in range(steps):
        step_rng = jax.random.fold_in(jax.random.fold_in(run_key, i), 0)
        params, batch_stats, opt_state, loss = step_fn(
            params, batch_stats, opt_state, data,
            jnp.asarray(sched.indices[i]), jnp.asarray(sched.mask[i]),
            step_rng,
        )
        manual_losses.append(float(loss))

    # Per-step losses agree to float precision (empirically bit-identical on
    # most steps).
    np.testing.assert_allclose(
        result.losses[:, 0], np.array(manual_losses), rtol=1e-6
    )
    # Parameters agree leaf-by-leaf (the degenerate weighted psum is w*p/w —
    # float-rounding only), except the two BN-free bias directions (see
    # _BN_FREE_LEAVES note above), which are loss-invariant.
    fed_params = jax.tree.map(lambda l: np.asarray(l[0]), result.client_params)
    flat_fed = flatten_dict(fed_params, sep="/")
    flat_manual = flatten_dict(jax.tree.map(np.asarray, params), sep="/")
    assert flat_fed.keys() == flat_manual.keys()
    for key in flat_fed:
        if key in _BN_FREE_LEAVES:
            assert np.all(np.isfinite(flat_fed[key]))
            continue
        np.testing.assert_allclose(
            flat_fed[key], flat_manual[key], rtol=2e-4, atol=5e-6,
            err_msg=key,
        )


@pytest.mark.slow
def test_one_client_federation_tracks_avitm_fit():
    """Documented-divergence check vs AVITM.fit: same data/init/steps,
    different RNG streams (see module docstring) — trajectories agree in
    value, not bitwise."""
    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus

    seed, epochs = 0, 4
    corpus = generate_synthetic_corpus(
        vocab_size=60, n_topics=4, n_docs=80, nwords=(30, 60), n_nodes=1,
        frozen_topics=2, seed=7, materialize_docs=False,
    )
    idx2token = {i: f"wd{i}" for i in range(60)}
    d = BowDataset(X=corpus.nodes[0].bow, idx2token=idx2token)

    template = _make_model(d.vocab_size, epochs, seed=seed)
    trainer = FederatedTrainer(template, n_clients=1, seed=seed)
    result = trainer.fit([d])
    fed_epoch_losses = np.array(result.epoch_losses[0])

    central = _make_model(d.vocab_size, epochs, seed=seed)
    central.fit(BowDataset(X=corpus.nodes[0].bow, idx2token=idx2token))

    assert fed_epoch_losses.shape == (epochs,)
    assert np.all(np.isfinite(fed_epoch_losses))
    # both runs learn: loss decreases over training
    assert fed_epoch_losses[-1] < fed_epoch_losses[0]
    assert central.epoch_losses[-1] < central.epoch_losses[0]
    # same data/init/step-count, different RNG streams: final per-epoch
    # losses agree in value (not bitwise)
    final_central = central.epoch_losses[-1]
    assert abs(fed_epoch_losses[-1] - final_central) / final_central < 0.10
