"""Live ops endpoint + device-hook tests (tier-1): Prometheus text
exposition (counters/gauges/histograms, name sanitization, every line
format-parsable), the OpsServer routes against a live in-process HTTP
server, the FederatedServer's /status payload, the straggler detector's
z-score flagging, the RoundProfiler window state machine (monkeypatched
jax.profiler), and the CPU no-op of the device-memory monitor."""

import json
import re
import urllib.error
import urllib.request

import pytest

from gfedntm_tpu.utils.observability import (
    DeviceMemoryMonitor,
    MetricRegistry,
    MetricsLogger,
    OpsServer,
    RoundProfiler,
    StragglerDetector,
    parse_round_window,
    render_prometheus,
)

_PROM_LINE = re.compile(
    r"^(#.*|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+)$"
)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


# ---- Prometheus exposition ---------------------------------------------------

class TestRenderPrometheus:
    def test_counter_gauge_histogram_families(self):
        reg = MetricRegistry()
        reg.counter("rpc_calls").inc(3)
        reg.gauge("compression_ratio_sent").set(2.5)
        reg.gauge("unset_gauge")  # value None: must be omitted, not "None"
        h = reg.histogram("rpc_s/Federation.TrainStep", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = render_prometheus(reg.snapshot())
        lines = text.strip().splitlines()
        for line in lines:
            assert _PROM_LINE.match(line), f"bad line: {line!r}"
        assert "# TYPE gfedntm_rpc_calls_total counter" in lines
        assert "gfedntm_rpc_calls_total 3.0" in lines
        assert "gfedntm_compression_ratio_sent 2.5" in lines
        assert not any("unset_gauge" in ln and "None" in ln for ln in lines)
        # histogram: cumulative buckets + +Inf + sum/count, keyed label
        assert (
            'gfedntm_rpc_s_bucket{key="Federation.TrainStep",le="0.1"} 1'
            in lines
        )
        assert (
            'gfedntm_rpc_s_bucket{key="Federation.TrainStep",le="+Inf"} 2'
            in lines
        )
        assert 'gfedntm_rpc_s_count{key="Federation.TrainStep"} 2' in lines

    def test_slash_names_become_key_labels_and_sanitize(self):
        reg = MetricRegistry()
        reg.gauge("client_staleness_mb/client7").set(1)
        reg.gauge("device_bytes_in_use/tpu0").set(12345)
        reg.counter("weird-name/with spaces").inc()
        text = render_prometheus(reg.snapshot())
        for line in text.strip().splitlines():
            assert _PROM_LINE.match(line), f"bad line: {line!r}"
        assert 'gfedntm_client_staleness_mb{key="client7"} 1.0' in text
        assert 'gfedntm_device_bytes_in_use{key="tpu0"} 12345.0' in text
        assert 'gfedntm_weird_name_total{key="with spaces"} 1.0' in text

    def test_label_values_escaped(self):
        reg = MetricRegistry()
        reg.counter('odd/va"lue\\x').inc()
        text = render_prometheus(reg.snapshot())
        assert '{key="va\\"lue\\\\x"}' in text

    def test_empty_registry_renders_empty_exposition(self):
        assert render_prometheus({}) == "\n"


# ---- OpsServer routes --------------------------------------------------------

class TestOpsServer:
    def test_routes_against_live_server(self):
        reg = MetricRegistry()
        reg.counter("rpc_calls").inc(7)
        ops = OpsServer(
            registry=reg, status_fn=lambda: {"round": 4, "codec": "none"},
        )
        port = ops.start()
        try:
            base = f"http://127.0.0.1:{port}"
            code, ctype, body = _get(base + "/healthz")
            assert (code, body) == (200, b"ok\n")

            code, ctype, body = _get(base + "/metrics")
            assert code == 200 and ctype.startswith("text/plain")
            assert "version=0.0.4" in ctype
            text = body.decode()
            for line in text.strip().splitlines():
                assert _PROM_LINE.match(line), f"bad line: {line!r}"
            assert "gfedntm_rpc_calls_total 7.0" in text

            code, ctype, body = _get(base + "/status")
            assert code == 200 and ctype == "application/json"
            assert json.loads(body) == {"round": 4, "codec": "none"}

            with pytest.raises(urllib.error.HTTPError) as err:
                _get(base + "/bogus")
            assert err.value.code == 404
        finally:
            ops.stop()

    def test_status_fn_failure_is_500_not_crash(self):
        def boom():
            raise RuntimeError("status exploded")

        ops = OpsServer(status_fn=boom)
        port = ops.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"http://127.0.0.1:{port}/status")
            assert err.value.code == 500
            # the serving thread survived: healthz still answers
            code, _ctype, body = _get(f"http://127.0.0.1:{port}/healthz")
            assert (code, body) == (200, b"ok\n")
        finally:
            ops.stop()

    def test_no_status_fn_serves_empty_object(self):
        ops = OpsServer()
        port = ops.start()
        try:
            _code, _ctype, body = _get(f"http://127.0.0.1:{port}/status")
            assert json.loads(body) == {}
        finally:
            ops.stop()


class TestFederatedServerStatus:
    def test_status_of_idle_server_over_http(self, tmp_path):
        """/status against a live (pre-training) FederatedServer: round 0,
        declared codec/aggregator, empty membership — the content contract
        the live-run e2e (test_trace_plane) asserts mid-federation."""
        from gfedntm_tpu.federation.server import FederatedServer

        metrics = MetricsLogger(validate=True, node="server")
        server = FederatedServer(
            min_clients=2, family="avitm",
            model_kwargs=dict(n_components=3, hidden_sizes=(8,)),
            metrics=metrics, ops_port=0, wire_codec="delta+fp16",
            aggregator="fedadam",
        )
        addr = server.start("[::]:0")
        assert addr
        try:
            assert server.ops_actual_port
            base = f"http://127.0.0.1:{server.ops_actual_port}"
            status = json.loads(_get(base + "/status")[2])
            assert status["round"] == 0
            assert status["training_started"] is False
            assert status["training_done"] is False
            assert status["codec"] == "delta+fp16"
            assert status["aggregator"] == "fedadam"
            assert status["min_clients"] == 2
            # default view is the bounded SUMMARY (ISSUE 11): counts per
            # state, not an O(N) per-client roster
            assert status["clients"]["total"] == 0
            assert status["clients"]["by_status"] == {}
            assert status["stragglers"] == {
                "observed": 0, "flagged": 0, "top_slowest": [],
            }
            assert status["compression"] == {
                "ratio_sent": None, "ratio_recv": None,
            }
            # membership appears as soon as a client registers; the full
            # roster stays behind ?full=1
            server.federation.connect_vocab(5, ("tok",), 12.0)
            status = json.loads(_get(base + "/status")[2])
            assert status["clients"]["total"] == 1
            assert status["clients"]["by_status"] == {"active": 1}
            full = json.loads(_get(base + "/status?full=1")[2])
            (rec,) = full["clients"]
            assert rec["client_id"] == 5
            assert rec["status"] == "active"
            assert rec["nr_samples"] == 12.0
            assert rec["last_loss"] is None  # NaN must serialize as null
            assert full["stragglers"] == {}  # the raw per-client map
            (started,) = metrics.events("ops_server_started")
            assert started["port"] == server.ops_actual_port
        finally:
            server.stop()
        # stopped: the port no longer answers
        with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
            _get(base + "/healthz", timeout=2)

    def test_no_ops_port_starts_no_ops_server(self):
        from gfedntm_tpu.federation.server import FederatedServer

        server = FederatedServer(min_clients=1)
        server.start("[::]:0")
        try:
            assert server.ops_actual_port is None
            assert server._ops_server is None
        finally:
            server.stop()


# ---- straggler analytics -----------------------------------------------------

class TestStragglerDetector:
    def test_flags_outlier_after_history(self):
        reg = MetricRegistry()
        det = StragglerDetector(
            registry=reg, z_threshold=1.5, min_clients=3, min_rounds=3,
        )
        flagged = []
        for _ in range(5):
            flagged = det.observe_round({1: 0.10, 2: 0.11, 3: 0.10, 4: 0.50})
        assert [f["client"] for f in flagged] == [4]
        assert flagged[0]["z"] > 1.5
        assert flagged[0]["ewma_s"] == pytest.approx(0.5, rel=0.05)
        # per-client EWMA gauges exist for all observed clients
        for cid in (1, 2, 3, 4):
            assert reg.get(f"client_step_ewma_s/client{cid}") is not None
        status = det.status()
        assert status["4"]["straggler"] is True
        assert status["1"]["straggler"] is False
        assert status["4"]["z"] > status["1"]["z"]

    def test_needs_population_and_history(self):
        det = StragglerDetector(min_clients=3, min_rounds=3)
        # two clients: never enough population for a z-score
        for _ in range(10):
            assert det.observe_round({1: 0.1, 2: 9.9}) == []
        det = StragglerDetector(min_clients=3, min_rounds=3)
        # rounds 1-2: history too short even with a wild outlier
        assert det.observe_round({1: 0.1, 2: 0.1, 3: 5.0}) == []
        assert det.observe_round({1: 0.1, 2: 0.1, 3: 5.0}) == []

    def test_uniform_population_never_flags(self):
        det = StragglerDetector(min_clients=3, min_rounds=1)
        for _ in range(5):
            assert det.observe_round({1: 0.2, 2: 0.2, 3: 0.2}) == []

    def test_recovered_client_unflags(self):
        # z_threshold 1.5: one outlier among n clients caps at z=sqrt(n-1),
        # so the default 2.0 is unreachable in a 4-client population
        det = StragglerDetector(
            min_clients=3, min_rounds=2, alpha=0.9, z_threshold=1.5,
        )
        for _ in range(4):
            det.observe_round({1: 0.1, 2: 0.1, 3: 0.1, 4: 1.0})
        assert det.status()["4"]["straggler"] is True
        for _ in range(4):
            det.observe_round({1: 0.1, 2: 0.1, 3: 0.1, 4: 0.1})
        assert det.status()["4"]["straggler"] is False

    def test_forget_evicts_dropped_client_from_population(self):
        det = StragglerDetector(min_clients=3, min_rounds=2, z_threshold=1.5)
        for _ in range(4):
            det.observe_round({1: 0.1, 2: 0.1, 3: 0.1, 4: 10.0})
        assert det.status()["4"]["straggler"] is True
        det.forget(4)  # dropped: its frozen 10s EWMA must leave the stats
        assert "4" not in det.status()
        # the remaining tight cluster is undisturbed by the ghost; a NEW
        # modest straggler is still detectable against it
        flagged = []
        for _ in range(4):
            flagged = det.observe_round({1: 0.1, 2: 0.1, 3: 0.1, 5: 0.5})
        assert [f["client"] for f in flagged] == [5]
        det.forget(99)  # unknown id is a no-op

    def test_rejects_bad_alpha(self):
        with pytest.raises(ValueError):
            StragglerDetector(alpha=0.0)


# ---- device profiler window --------------------------------------------------

class TestRoundProfiler:
    def test_window_parse(self):
        assert parse_round_window("1:3") == (1, 3)
        assert parse_round_window("4") == (4, 5)
        for bad in ("", "x", "3:3", "5:2", "-1:2", "1:2:3"):
            with pytest.raises(ValueError):
                parse_round_window(bad)

    def test_none_dir_is_total_noop(self):
        prof = RoundProfiler(None)
        for r in range(5):
            prof.observe(r)
        prof.close()  # never touches jax

    def test_window_drives_start_and_stop(self, monkeypatch, tmp_path):
        import jax

        calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda d, **kw: calls.append(("start", d)),
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append(("stop", None)),
        )
        log = MetricsLogger(validate=True)
        prof = RoundProfiler(str(tmp_path), rounds="2:4", metrics=log)
        for r in range(6):
            prof.observe(r)
        prof.close()
        assert calls == [("start", str(tmp_path)), ("stop", None)]
        (started,) = log.events("profiler_started")
        assert started["round"] == 2 and started["dir"] == str(tmp_path)
        (stopped,) = log.events("profiler_stopped")
        assert stopped["round"] == 4

    def test_close_stops_open_window(self, monkeypatch, tmp_path):
        import jax

        calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda d, **kw: calls.append("start"),
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append("stop"),
        )
        prof = RoundProfiler(str(tmp_path), rounds="0:100")
        prof.observe(0)
        prof.close()  # run ended mid-window
        assert calls == ["start", "stop"]
        prof.close()  # idempotent
        assert calls == ["start", "stop"]

    def test_profiler_backend_failure_disables_not_raises(
        self, monkeypatch, tmp_path
    ):
        import jax

        def explode(d, **kw):
            raise RuntimeError("no profiler in this backend")

        monkeypatch.setattr(jax.profiler, "start_trace", explode)
        log = MetricsLogger(validate=True)
        prof = RoundProfiler(str(tmp_path), rounds="0:2", metrics=log)
        prof.observe(0)  # swallowed, disables
        prof.observe(1)
        prof.close()
        assert log.events("profiler_started") == []
        assert log.registry.get("profiler_failures").value == 1


# ---- device memory -----------------------------------------------------------

class TestDeviceMemoryMonitor:
    def test_sample_is_safe_everywhere(self):
        """On CPU memory_stats() is unavailable — sample() must probe once,
        then no-op; on accelerators it fills device_bytes_in_use gauges.
        Either way: no exceptions, snapshot stays serializable."""
        reg = MetricRegistry()
        mon = DeviceMemoryMonitor(reg)
        mon.sample()
        mon.sample()  # second call takes the cached-probe path
        snap = reg.snapshot()
        json.dumps(snap)  # JSON-safe regardless of platform
        for name, m in snap.items():
            if name.startswith("device_bytes_in_use/"):
                assert m["type"] == "gauge" and m["value"] >= 0

    def test_probe_failure_leaves_empty_device_list(self, monkeypatch):
        import gfedntm_tpu.utils.observability as obs

        mon = DeviceMemoryMonitor(MetricRegistry())
        monkeypatch.setattr(
            obs.DeviceMemoryMonitor, "_probe", lambda self: [],
        )
        mon.sample()
        assert mon._devices == []
