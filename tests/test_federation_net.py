"""Network federation end-to-end tests (C2-C6, C10): real gRPC on localhost.

The reference's multi-node test story is docker-compose (SURVEY.md §4); here
server + N clients run as threads in one process over real sockets, which
exercises the full wire path (proto codecs, consensus quorum, per-minibatch
poll/average/push, stop broadcast, artifacts).
"""

import threading

import numpy as np
import pytest

from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.federation import codec
from gfedntm_tpu.federation.client import Client
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.federation.server import FederatedServer


# ---- codec unit tests ------------------------------------------------------

def test_array_roundtrip():
    for arr in (
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.array(3, dtype=np.int64),
        np.random.default_rng(0).normal(size=(2, 3, 4)),
        np.array([True, False]),
    ):
        rec = codec.array_to_record("x", arr)
        out = codec.record_to_array(rec)
        np.testing.assert_array_equal(out, np.asarray(arr))


def test_array_rejects_unknown_dtype():
    with pytest.raises(TypeError):
        codec.array_to_record("x", np.array(["a"], dtype=object))


def test_tree_roundtrip_with_optax_state():
    import optax

    params = {"a": np.ones((2, 2), np.float32), "b": {"c": np.zeros(3)}}
    tx = optax.adam(1e-3)
    state = tx.init(params)
    bundle = codec.tree_to_bundle(state)
    restored = codec.bundle_to_tree(state, bundle)
    flat_a = [np.asarray(x) for x in
              __import__("jax").tree_util.tree_leaves(state)]
    flat_b = [np.asarray(x) for x in
              __import__("jax").tree_util.tree_leaves(restored)]
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_array_equal(x, y)


def test_bundle_to_tree_detects_mismatch():
    bundle = codec.tree_to_bundle({"a": np.ones(2)})
    with pytest.raises(ValueError):
        codec.bundle_to_tree({"b": np.ones(2)}, bundle)  # path mismatch
    with pytest.raises(ValueError):
        codec.bundle_to_tree({"a": np.ones(3)}, bundle)  # shape mismatch
    with pytest.raises(ValueError):
        codec.bundle_to_tree({"a": np.ones(2), "c": np.ones(1)}, bundle)


def test_flatdict_roundtrip():
    d = {"params/beta": np.random.default_rng(0).normal(size=(4, 9)),
         "params/prior_mean": np.zeros(4, np.float32)}
    out = codec.bundle_to_flatdict(codec.flatdict_to_bundle(d))
    assert set(out) == set(d)
    for k in d:
        np.testing.assert_array_equal(out[k], np.asarray(d[k]))


# ---- end-to-end federation over localhost ----------------------------------

def _make_corpora(n_clients: int, docs: int = 18, seed: int = 0):
    rng = np.random.default_rng(seed)
    words = [f"word{i:03d}" for i in range(90)]
    corpora = []
    for c in range(n_clients):
        lo = 20 * c
        # sizes diverge enough that steps-per-epoch differ across clients
        # (18 vs 40 docs at batch 8 -> 3 vs 5 steps), so the early-finisher
        # drop-out path of the aggregation loop is exercised
        docs_c = [
            " ".join(rng.choice(words[lo:lo + 60], size=25))
            for _ in range(docs + 22 * c)
        ]
        corpora.append(RawCorpus(documents=docs_c))
    return corpora


@pytest.mark.slow
def test_grpc_federation_end_to_end(tmp_path):
    n_clients = 2
    model_kwargs = dict(
        n_components=4, hidden_sizes=(16, 16), batch_size=8, num_epochs=2,
        seed=0,
    )
    server = FederatedServer(
        min_clients=n_clients, family="avitm", model_kwargs=model_kwargs,
        max_iters=500, save_dir=str(tmp_path / "server"),
    )
    server_addr = server.start("[::]:0")

    corpora = _make_corpora(n_clients)
    clients = [
        Client(
            client_id=c + 1, corpus=corpora[c], server_address=server_addr,
            max_features=80, save_dir=str(tmp_path / f"client{c + 1}"),
        )
        for c in range(n_clients)
    ]
    threads = [
        threading.Thread(target=cl.run, daemon=True) for cl in clients
    ]
    for t in threads:
        t.start()

    assert server.wait_done(timeout=300), "federated training did not finish"
    for t in threads:
        t.join(timeout=60)

    # all clients finished their epochs and produced artifacts
    for cl in clients:
        assert cl.stopped.is_set()
        assert cl.results is not None
        thetas = cl.results["thetas"]
        np.testing.assert_allclose(thetas.sum(axis=1), 1.0, rtol=1e-5)
        assert cl.stepper.current_epoch == model_kwargs["num_epochs"]
        assert (tmp_path / f"client{cl.client_id}" / "model.npz").exists()

    # server artifact: global betas over the consensus vocabulary
    assert (tmp_path / "server" / "server_model.npz").exists()
    assert server.global_betas.shape == (
        model_kwargs["n_components"], len(server.global_vocab)
    )
    assert np.isfinite(server.global_betas).all()

    # clients hold identical shared params after the final exchange...
    g0 = clients[0].stepper.get_gradients()
    g1 = clients[1].stepper.get_gradients()
    # ...except leaves whose last local step ran after the last aggregate
    # (clients with unequal epoch lengths step past the final average, as in
    # the reference). Betas must match the server's last average:
    last_avg = server.last_average
    for k in last_avg:
        assert k in g0 and k in g1

    # consensus vocabulary is the sorted union of client vocabularies
    tokens = server.global_vocab.tokens
    assert list(tokens) == sorted(tokens)

    # unequal epoch lengths: the late-running client keeps averaging after
    # the early one finishes; a stale total-weight denominator would have
    # shrunk the betas toward zero exponentially (regression guard)
    assert np.abs(server.global_betas).max() > 1e-3
    server.stop()
    for cl in clients:
        cl.shutdown()


@pytest.mark.slow
def test_grpc_federation_stop_before_first_epoch(tmp_path):
    """max_iters smaller than steps-per-epoch: clients must still finalize
    (best_components falls back to the current beta)."""
    server = FederatedServer(
        min_clients=1, family="avitm",
        model_kwargs=dict(
            n_components=3, hidden_sizes=(8, 8), batch_size=8, num_epochs=5,
            seed=0,
        ),
        max_iters=2, save_dir=str(tmp_path),
    )
    addr = server.start("[::]:0")
    client = Client(
        client_id=1, corpus=_make_corpora(1, docs=30)[0], server_address=addr,
        max_features=60, save_dir=str(tmp_path / "c1"),
    )
    t = threading.Thread(target=client.run, daemon=True)
    t.start()
    assert server.wait_done(timeout=180)
    t.join(timeout=60)
    assert client.results is not None
    assert (tmp_path / "c1" / "model.npz").exists()
    server.stop()
    client.shutdown()


@pytest.mark.slow
def test_grpc_ctm_federation_with_epoch_snapshots(tmp_path):
    """CTM over the network path: consensus ships contextual hyperparams,
    clients train a ZeroShotTM, and — matching ``federated_ctm.py:150-159``
    — every completed epoch writes a model snapshot under the client's
    save_dir."""
    epochs = 2
    server = FederatedServer(
        min_clients=1, family="ctm",
        model_kwargs=dict(
            n_components=3, hidden_sizes=(8, 8), batch_size=8,
            num_epochs=epochs, contextual_size=12, inference_type="zeroshot",
            seed=0,
        ),
        max_iters=200, save_dir=str(tmp_path / "server"),
    )
    addr = server.start("[::]:0")

    corpus = _make_corpora(1, docs=18)[0]
    rng = np.random.default_rng(3)
    corpus = RawCorpus(
        documents=corpus.documents,
        embeddings=rng.normal(size=(len(corpus), 12)).astype(np.float32),
    )
    client = Client(
        client_id=1, corpus=corpus, server_address=addr, max_features=60,
        save_dir=str(tmp_path / "c1"),
    )
    t = threading.Thread(target=client.run, daemon=True)
    t.start()
    assert server.wait_done(timeout=300)
    t.join(timeout=60)

    assert client.stepper.finished
    snap_dir = tmp_path / "c1" / "epoch_snapshots"
    for epoch in range(epochs):
        assert (snap_dir / f"epoch_{epoch}.npz").exists(), epoch
    assert (tmp_path / "c1" / "model.npz").exists()
    server.stop()
    client.shutdown()


@pytest.mark.slow
def test_grpc_ctm_federation_cohort_pacing_with_quality_plane(tmp_path):
    """ISSUE 14 satellite: CTM through cohort pacing + the update gate +
    quality monitoring TOGETHER — the network path existed per-plane but
    the composition had never run. Asserts finite betas and a rendered
    quality report from the JSONL stream alone."""
    from gfedntm_tpu.utils.observability import (
        MetricsLogger,
        format_quality_report,
        read_metrics,
        summarize_model_quality,
    )

    corpora = _make_corpora(2, docs=18)
    ref_path = tmp_path / "quality_ref.txt"
    ref_path.write_text(
        "\n".join(d for c in corpora for d in c.documents) + "\n"
    )
    metrics = MetricsLogger(
        str(tmp_path / "server" / "metrics.jsonl"), node="server",
        validate=True,
    )
    server = FederatedServer(
        min_clients=2, family="ctm",
        model_kwargs=dict(
            n_components=3, hidden_sizes=(8, 8), batch_size=8,
            num_epochs=2, contextual_size=12, inference_type="zeroshot",
            seed=0,
        ),
        max_iters=200, save_dir=str(tmp_path / "server"),
        metrics=metrics, pacing_policy="cohort:1", local_steps=2,
        quality_every=1, quality_ref=str(ref_path), quality_topn=6,
    )
    addr = server.start("[::]:0")

    rng = np.random.default_rng(3)
    clients = []
    for c, corpus in enumerate(corpora):
        corpus = RawCorpus(
            documents=corpus.documents,
            embeddings=rng.normal(
                size=(len(corpus), 12)
            ).astype(np.float32),
        )
        clients.append(Client(
            client_id=c + 1, corpus=corpus, server_address=addr,
            max_features=90, save_dir=str(tmp_path / f"c{c + 1}"),
        ))
    threads = [
        threading.Thread(target=cl.run, daemon=True) for cl in clients
    ]
    for t in threads:
        t.start()
    assert server.wait_done(timeout=300)
    for t in threads:
        t.join(timeout=60)
    server.stop()
    for cl in clients:
        cl.shutdown()
    metrics.snapshot_registry()
    metrics.close()

    # finite betas out of the composed path
    assert np.isfinite(server.global_betas).all()
    # the quality plane actually ran per averaged round, with NPMI
    records = read_metrics(str(tmp_path / "server" / "metrics.jsonl"))
    summary = summarize_model_quality(records)
    rows = summary["quality"]
    assert rows, "no quality_computed rounds in the stream"
    assert any(r.get("npmi") is not None for r in rows)
    # cohort pacing was live (cohort_sampled events present)
    assert any(r.get("event") == "cohort_sampled" for r in records)
    # and the report renders from JSONL alone
    report = format_quality_report(summary)
    assert "round" in report.lower()


def test_ready_for_training_during_shutdown_window():
    """A ReadyForTraining landing in the shutdown window — after the
    stop-broadcast snapshot (``_stopping`` set) but before
    ``training_done`` — must get code=1, not be registered to wait for
    polls that will never come."""
    server = FederatedServer(
        min_clients=1, family="avitm",
        model_kwargs=dict(
            n_components=3, hidden_sizes=(8, 8), batch_size=8, num_epochs=1,
            seed=0,
        ),
    )
    server._stopping.set()
    assert not server.training_done.is_set()
    ack = server.ReadyForTraining(
        pb.JoinRequest(client_id=7, address="localhost:1"), None
    )
    assert ack.code == 1
    assert server._train_thread is None
    assert len(server.federation) == 0  # turned away before registration


@pytest.mark.slow
def test_grpc_federation_single_client(tmp_path):
    server = FederatedServer(
        min_clients=1, family="avitm",
        model_kwargs=dict(
            n_components=3, hidden_sizes=(8, 8), batch_size=8, num_epochs=1,
            seed=0,
        ),
        max_iters=100, save_dir=str(tmp_path),
    )
    addr = server.start("[::]:0")
    client = Client(
        client_id=1, corpus=_make_corpora(1)[0], server_address=addr,
        max_features=60,
    )
    t = threading.Thread(target=client.run, daemon=True)
    t.start()
    assert server.wait_done(timeout=180)
    t.join(timeout=30)
    assert client.stepper.finished
    assert server.global_iterations == client.stepper.current_mb
    server.stop()
    client.shutdown()


@pytest.mark.slow
def test_client_rejoin_after_drop(tmp_path):
    """Elastic recovery: a client that dies mid-training is dropped
    fail-soft; the same client id rejoining on a NEW port re-enters the
    round (the reference is fail-stop — SURVEY.md §5).

    Client 1's corpus is sized so its epochs exceed max_iters: the round
    loop provably outlives the drop/rejoin window, and the federation ends
    at the max_iters cap with the rejoined client fully trained."""
    import time

    model_kwargs = dict(
        n_components=3, hidden_sizes=(8, 8), batch_size=8, num_epochs=2,
        seed=0,
    )
    server = FederatedServer(
        min_clients=2, family="avitm", model_kwargs=model_kwargs,
        max_iters=5000, save_dir=str(tmp_path / "server"),
    )
    server_addr = server.start("[::]:0")

    rng = np.random.default_rng(0)
    words = [f"word{i:03d}" for i in range(90)]
    corpus_a = RawCorpus(documents=[
        " ".join(rng.choice(words, size=25)) for _ in range(2500)
    ])
    corpus_b = RawCorpus(documents=[
        " ".join(rng.choice(words, size=25)) for _ in range(400)
    ])

    cl_a = Client(
        client_id=1, corpus=corpus_a, server_address=server_addr,
        max_features=80, save_dir=str(tmp_path / "client1"),
    )
    cl_b = Client(
        client_id=2, corpus=corpus_b, server_address=server_addr,
        max_features=80, save_dir=str(tmp_path / "client2"),
    )
    t_a = threading.Thread(target=cl_a.run, daemon=True)
    t_b = threading.Thread(target=cl_b.run, daemon=True)
    t_a.start()
    t_b.start()

    # wait until training is underway, then crash client 2's serving side
    deadline = time.time() + 180
    while time.time() < deadline:
        recs = {c.client_id: c for c in server.federation.get_clients()}
        if 2 in recs and recs[2].current_mb > 0:
            break
        time.sleep(0.2)
    else:
        pytest.fail("training never started")
    # the drop path must actually be exercised: client 2 (400 docs / batch 8
    # / 2 epochs = 100 rounds) cannot have finished legitimately yet
    assert not recs[2].finished
    cl_b._grpc_server.stop(0)

    # server must drop client 2 fail-soft
    deadline = time.time() + 180
    while time.time() < deadline:
        recs = {c.client_id: c for c in server.federation.get_clients()}
        if recs[2].finished:
            break
        time.sleep(0.2)
    else:
        pytest.fail("client 2 was never dropped")

    # same client id rejoins with fresh state on a fresh port
    cl_b2 = Client(
        client_id=2, corpus=corpus_b, server_address=server_addr,
        max_features=80, save_dir=str(tmp_path / "client2b"),
    )
    t_b2 = threading.Thread(target=cl_b2.run, daemon=True)
    t_b2.start()

    assert server.wait_done(timeout=540), "federation did not finish"
    t_b2.join(timeout=60)

    # the rejoined client trained to completion and produced artifacts
    assert cl_b2.stopped.is_set()
    assert cl_b2.results is not None
    assert cl_b2.stepper.current_epoch == model_kwargs["num_epochs"]
    rec2 = {c.client_id: c for c in server.federation.get_clients()}[2]
    assert rec2.current_mb > 0
    server.stop()
    cl_a.shutdown()
    cl_b2.shutdown()


@pytest.mark.slow
def test_grpc_federation_local_steps(tmp_path):
    """E>1 over the wire: the server's StepRequest carries local_steps,
    each client runs E-1 aggregate-free local steps (advance_local) per
    round, and the run completes with server artifacts — the network
    analogue of FederatedTrainer(local_steps=E)."""
    model_kwargs = dict(
        n_components=4, hidden_sizes=(16, 16), batch_size=8, num_epochs=2,
        seed=0,
    )
    server = FederatedServer(
        min_clients=2, family="avitm", model_kwargs=model_kwargs,
        max_iters=500, save_dir=str(tmp_path / "server"), local_steps=3,
    )
    server_addr = server.start("[::]:0")
    corpora = _make_corpora(2)
    clients = [
        Client(
            client_id=c + 1, corpus=corpora[c], server_address=server_addr,
            max_features=80, save_dir=str(tmp_path / f"client{c + 1}"),
        )
        for c in range(2)
    ]
    threads = [
        threading.Thread(target=cl.run, daemon=True) for cl in clients
    ]
    for t in threads:
        t.start()
    assert server.wait_done(timeout=300), "E=3 federation did not finish"
    for t in threads:
        t.join(timeout=60)

    for cl in clients:
        assert cl.stopped.is_set()
        assert cl.results is not None
        # budget is exact: rounds truncate so no client trains past
        # num_epochs (the SPMD forced-final-exchange semantics)
        assert cl.stepper.current_epoch == model_kwargs["num_epochs"]
        spe = -(-len(cl.stepper.model.train_data) // model_kwargs["batch_size"])
        assert cl.stepper.current_mb == spe * model_kwargs["num_epochs"]
    assert np.isfinite(server.global_betas).all()
    # E=3 with 3-5 steps/epoch x 2 epochs -> far fewer exchange rounds
    # than minibatches: the server iterated at most ceil(10/3)+1 rounds.
    assert server.global_iterations <= 5
    server.stop()
    for cl in clients:
        cl.shutdown()


def test_server_rejects_invalid_local_steps():
    with pytest.raises(ValueError):
        FederatedServer(min_clients=1, local_steps=0)


def test_step_reply_nr_samples_sums_all_local_minibatches():
    """ADVICE r5: with local_steps E>1 the StepReply must report the
    samples consumed across ALL E minibatches (sum of mask sums), not the
    last — possibly partial tail — batch, or sample-weighted FedAvg weights
    a whole E-step round by one batch."""
    import logging

    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.federated.stepper import FederatedStepper
    from gfedntm_tpu.federation.client import FederatedClientServicer
    from gfedntm_tpu.models.avitm import AVITM

    docs, vocab, batch = 10, 30, 4  # epoch = batches of 4, 4, 2
    rng = np.random.default_rng(0)
    dataset = BowDataset(
        X=rng.integers(0, 3, size=(docs, vocab)).astype(np.float32),
        idx2token={i: f"wd{i}" for i in range(vocab)},
    )
    model = AVITM(
        input_size=vocab, n_components=3, hidden_sizes=(8,),
        batch_size=batch, num_epochs=1, seed=0,
    )
    stepper = FederatedStepper(model)
    stepper.pre_fit(dataset)
    servicer = FederatedClientServicer(
        1, stepper, on_stop=lambda: None,
        logger=logging.getLogger("test"),
    )
    reply = servicer.TrainStep(
        pb.StepRequest(global_iter=0, local_steps=3), None
    )
    # the whole epoch ran in one round: 4 + 4 + 2 samples, not the tail 2
    assert reply.nr_samples == docs
    assert stepper._last_batch_size == docs - 2 * batch


def test_fedavg_weights_by_reply_samples_with_join_time_fallback():
    """The server's aggregate must weight each contributor by the samples
    its reply says it consumed THIS round; a reply that reports none (a
    pre-plane client) falls back to the join-time corpus size."""
    from gfedntm_tpu.federation import codec
    from gfedntm_tpu.federation.registry import ClientRecord
    from gfedntm_tpu.federation.server import build_template_model

    server = FederatedServer(
        min_clients=2, family="avitm",
        model_kwargs=dict(n_components=3, hidden_sizes=(8,)),
    )
    server.template = build_template_model(
        "avitm", 30, dict(n_components=3, hidden_sizes=(8,))
    )
    tmpl = server._shared_template()
    bundle = codec.flatdict_to_bundle(tmpl)
    replies = [
        (ClientRecord(1, nr_samples=100.0),
         pb.StepReply(client_id=1, shared=bundle, nr_samples=24.0)),
        (ClientRecord(2, nr_samples=50.0),
         pb.StepReply(client_id=2, shared=bundle)),  # reports nothing
    ]
    out = server._collect_snapshots(replies, iteration=0)
    assert [w for w, _snap in out] == [24.0, 50.0]
