#!/usr/bin/env python
"""Flight-recorder overhead bench: the BENCH_FORENSICS artifact (ISSUE 19).

The flight ring taps EVERY record the node's MetricsLogger emits, so its
cost must be marginal by construction (one attribute load when absent, a
lock-guarded deque append when armed). This bench runs the same
simulated loopback federation (real wire / codec / pacing planes,
stubbed learning) twice — flight recorder ON (ring + trigger seam armed
on the server's logger, registry snapshots folding in) vs OFF — and
compares median round wall-clock from the server's own ``span`` events.

It also measures the capture path itself: with the ring filled to its
full configured depth (the worst realistic bundle), the time to snapshot
ring + process + stacks into an atomic bundle, and the bundle's on-disk
size.

Acceptance bar (ISSUE 19): recorder overhead < 1% of round wall-clock.
Exit 1 when breached.

Usage:
    python scripts/forensics_bench.py               # -> BENCH_FORENSICS_r01.json
    python scripts/forensics_bench.py --rounds 8 --clients 8 --vocab 2000
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, REPO)

OUT_PATH = os.path.join(REPO, "BENCH_FORENSICS_r01.json")
OVERHEAD_BOUND = 0.01


def run_config(forensics: bool, n_clients: int, vocab: int,
               rounds: int) -> dict:
    """One federation run; returns the median round seconds."""
    from gfedntm_tpu.federation.simfleet import make_sim_fleet
    from gfedntm_tpu.utils import flightrec
    from gfedntm_tpu.utils.observability import MetricsLogger

    server_m = MetricsLogger(validate=True, node="server")
    save_dir = tempfile.mkdtemp(prefix="forensics-bench-")
    if forensics:
        recorder = flightrec.FlightRecorder(registry=server_m.registry)
        server_m.recorder = recorder
        flightrec.IncidentTrigger(
            recorder, os.path.join(save_dir, "incidents"),
            metrics=server_m, node="server",
        )
    t0 = time.perf_counter()
    server, _servicers, _template = make_sim_fleet(
        n_clients,
        vocab_size=vocab,
        steps=rounds + 2,  # nobody finishes before max_iters ends the run
        pacing_policy="sync",
        max_iters=rounds,
        save_dir=save_dir,
        checkpoint_every=0,
        journal_every=0,
        metrics=server_m,
    )
    assert server.wait_done(timeout=600), "bench federation did not finish"
    wall_s = time.perf_counter() - t0
    server.stop()

    round_s = [
        r["seconds"] for r in server_m.events("span")
        if r.get("name") == "round"
    ]
    out = {
        "forensics": forensics,
        "rounds": int(server.global_iterations),
        "median_round_s": statistics.median(round_s) if round_s else 0.0,
        "wall_s": round(wall_s, 3),
    }
    if forensics:
        out["ring_records"] = len(server_m.recorder)
        assert out["ring_records"] > 0, (
            "forensics ON but the ring stayed empty — the tap is not "
            "exercising what this bench measures"
        )
    return out


def measure_capture(ring_depth: int, repeats: int) -> dict:
    """Capture latency + bundle size with the ring at full depth — the
    worst realistic bundle a trigger can dump."""
    from gfedntm_tpu.utils import flightrec
    from gfedntm_tpu.utils.observability import MetricsLogger

    m = MetricsLogger(validate=True, node="server")
    recorder = flightrec.FlightRecorder(max_entries=ring_depth)
    m.recorder = recorder
    dump_dir = tempfile.mkdtemp(prefix="forensics-capture-")
    trigger = flightrec.IncidentTrigger(
        recorder, dump_dir, metrics=m, node="server", debounce_s=0.0,
        max_bundles=repeats + 1,
    )
    # A representative record mix: schema'd logger events plus the
    # fine-grained notes the production hot paths ring.
    for i in range(ring_depth):
        if i % 3 == 0:
            m.log("checkpoint", round=i)
        elif i % 3 == 1:
            recorder.note("gate_verdict", client=i % 8, round=i,
                          verdict="accepted", norm=1.25)
        else:
            recorder.note("poll_dispatch", client=i % 8, round=i,
                          deadline_s=30.0)
    laps, sizes = [], []
    for i in range(repeats):
        t0 = time.perf_counter()
        path = trigger.capture("slo_alert", incident_id=f"bench{i}")
        laps.append(time.perf_counter() - t0)
        sizes.append(os.path.getsize(path))
    return {
        "ring_depth": ring_depth,
        "capture_ms": round(statistics.median(laps) * 1e3, 3),
        "bundle_bytes": int(statistics.median(sizes)),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--clients", type=int, default=8)
    # Same weighting rationale as telemetry_bench: the ring cost is
    # fixed per emitted record, so the vocab sets the round weight the
    # overhead is measured against (the stub fleet's unloaded rounds
    # would measure the sim's floor, not the tap's marginal cost).
    p.add_argument("--vocab", type=int, default=12_000)
    p.add_argument("--rounds", type=int, default=12)
    p.add_argument("--repeats", type=int, default=2)
    p.add_argument("--ring_depth", type=int, default=2048)
    p.add_argument("--capture_repeats", type=int, default=5)
    p.add_argument("--out", default=OUT_PATH)
    args = p.parse_args(argv)

    # Best-of-N medians per config, OFF first: scheduler noise only ever
    # inflates a run, so the min is the honest per-round cost, and any
    # JIT/warmup asymmetry lands on (and favors) the OFF side.
    def best(forensics: bool) -> dict:
        runs = [
            run_config(forensics, args.clients, args.vocab, args.rounds)
            for _ in range(max(1, args.repeats))
        ]
        return min(runs, key=lambda r: r["median_round_s"])

    off = best(False)
    on = best(True)
    capture = measure_capture(args.ring_depth, args.capture_repeats)

    overhead = (
        (on["median_round_s"] - off["median_round_s"])
        / off["median_round_s"]
        if off["median_round_s"] else 0.0
    )
    result = {
        "bench": "forensics_overhead",
        "rev": "r01",
        "backend": "cpu",
        "clients": args.clients,
        "vocab": args.vocab,
        "rounds": args.rounds,
        "bound": OVERHEAD_BOUND,
        "off": off,
        "on": on,
        "overhead_round_s": round(overhead, 4),
        "capture": capture,
        "acceptance": {
            "recorder_overhead_lt_1pct": overhead < OVERHEAD_BOUND,
        },
    }

    from scripts.bench_schema import require

    require(result, "forensics_bench")
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(result, fh, indent=1)
            fh.write("\n")
    if not all(result["acceptance"].values()):
        print(
            f"flight-recorder overhead exceeds the {OVERHEAD_BOUND:.0%} "
            f"bound: round_s {overhead:+.2%}", file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
