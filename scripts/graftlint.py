#!/usr/bin/env python
"""Shim: run the graftlint static-analysis suite.

The implementation lives in ``gfedntm_tpu/analysis/`` (rules, baseline,
CLI) — this wrapper exists so the gate is invocable as a script next to
its siblings (``scripts/check.sh`` stage "graftlint"). Same flags, same
exit codes as ``python -m gfedntm_tpu.analysis``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
)

from gfedntm_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
