#!/usr/bin/env python
"""Control-plane scale bench: the BENCH_SCALE artifact (ISSUE 11).

Measures, on the simulated-client loopback fleet
(:mod:`gfedntm_tpu.federation.simfleet` — real wire/codec/gate/registry/
pacing planes, stubbed learning), how server peak RSS and per-round wire
bytes scale with the population N at fixed per-round fan K:

- ``cohort`` (K-of-N sampling) and ``push`` (client-initiated rounds,
  buffer B=K) must stay FLAT in N — the ISSUE 11 acceptance bar is
  <= 1.2x from N=1k to N=10k;
- the ``sync`` all-clients barrier is the baseline that grows ~N/1k x.

Each configuration runs in its OWN subprocess so ``ru_maxrss`` (a
process-lifetime high-water mark) cannot leak across configurations.

A second, in-process measurement drives the per-recipient downlink
encoder through a rotating K-of-N cohort and compares its sent bytes
against the PR 10 fleet-consensus behaviour (rotation => every push
self-contained): the acceptance bar is a > 2x measured reduction.

Usage:
    python scripts/scale_bench.py                 # full matrix -> BENCH_SCALE_r01.json
    python scripts/scale_bench.py --single cohort 1000 16 6   # one config, JSON line
"""

from __future__ import annotations

import json
import math
import os
import resource
import subprocess
import sys
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

OUT_PATH = os.path.join(REPO, "BENCH_SCALE_r01.json")

#: (mode, N, fan K/B, rounds). Sync runs fewer rounds — each one touches
#: the whole population.
MATRIX = [
    ("cohort", 1_000, 16, 6),
    ("cohort", 10_000, 16, 6),
    ("push", 1_000, 16, 6),
    ("push", 10_000, 16, 6),
    ("sync", 1_000, 0, 2),
    ("sync", 10_000, 0, 2),
]


def _rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def run_single(mode: str, n: int, fan: int, rounds: int) -> dict:
    """One configuration in THIS process; returns the result record."""
    import tempfile

    from gfedntm_tpu.federation.simfleet import make_sim_fleet

    rss_before = _rss_mb()
    save_dir = tempfile.mkdtemp(prefix=f"scale-{mode}-{n}-")
    pacing = {
        "cohort": f"cohort:{fan}",
        "push": f"push:{fan}",
        "sync": "sync",
    }[mode]
    t0 = time.perf_counter()
    server, servicers, template = make_sim_fleet(
        n,
        steps=rounds + 2,  # nobody finishes before max_iters ends the run
        pacing_policy=pacing,
        max_iters=rounds,
        save_dir=save_dir,
        checkpoint_every=0,
        journal_every=0,
        round_backoff_s=0.02,
    )
    setup_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    counter = server.byte_counter

    def rounds_done_now() -> bool:
        return int(server.global_iterations) >= rounds

    round_bytes = None
    if mode == "push":
        # Single-threaded driver: round-robin client-initiated pushes
        # into the live PushEngine until it completes max_iters
        # aggregations (subsequent pushes answer stop=True).
        order = sorted(servicers)
        i = 0
        while not server.training_done.is_set() and not rounds_done_now():
            engine = server._engine
            if engine is not None:
                # Real clients push at their local-round cadence, so the
                # buffer hovers near B; an unthrottled driver would grow
                # the drain with the engine's O(N) tick time and measure
                # itself, not the server.
                while (
                    engine.status().get("buffer_depth", 0) >= fan
                    and not server.training_done.is_set()
                    and not rounds_done_now()
                ):
                    time.sleep(0.001)
            cid = order[i % len(order)]
            i += 1
            servicer = servicers[cid]
            if servicer.finished:
                continue
            update = servicer.build_update(template)
            agg = server.PushUpdate(update, None)
            counter.note(agg, update)
            servicer.apply(agg)
        # Round-attributable bytes: snapshot BEFORE the stop broadcast
        # (a one-time O(N) fan-out of ~10-byte stop messages that is not
        # per-round cost).
        round_bytes = counter.sent + counter.recv
        server.wait_done(timeout=600)
    else:
        while not server.training_done.is_set():
            if rounds_done_now() and round_bytes is None:
                round_bytes = counter.sent + counter.recv
            if server.training_done.wait(0.05):
                break
        assert server.wait_done(timeout=900), f"{mode} N={n} did not finish"
        if round_bytes is None:
            round_bytes = counter.sent + counter.recv
    run_s = time.perf_counter() - t1
    rounds_done = int(server.global_iterations)
    server.stop()
    return {
        "mode": mode,
        "n_clients": n,
        "fan": fan,
        "rounds": rounds_done,
        "peak_rss_mb": round(_rss_mb(), 1),
        "rss_before_mb": round(rss_before, 1),
        "bytes_per_round": round_bytes / max(1, rounds_done),
        "loopback_calls": counter.calls,
        "setup_s": round(setup_s, 2),
        "run_s": round(run_s, 2),
    }


def rotation_codec_measurement(
    n: int = 48, k: int = 8, rounds: int = 48, d: int = 40_000,
    codec_spec: str = "delta+topk:0.02",
) -> dict:
    """Per-recipient delta encoding vs the PR 10 fleet-consensus rule
    under a rotating K-of-N cohort, measured at the session level: the
    new encoder serves chain deltas + exact catch-ups; the old rule
    degraded every rotating-cohort push to a self-contained bundle."""
    import numpy as np

    from gfedntm_tpu.federation.compression import DownlinkEncoder, WireCodec

    rng = np.random.default_rng(0)
    state = {"plane": rng.standard_normal(d).astype(np.float32)}
    wc = WireCodec(codec_spec)
    enc_new = DownlinkEncoder(wc, max_views=4 * math.ceil(n / k))
    enc_old = DownlinkEncoder(WireCodec(codec_spec))
    acked: dict[int, int] = {}
    bytes_new = 0
    bytes_old = 0
    for r in range(rounds):
        state = {
            "plane": state["plane"]
            + 1e-3 * rng.standard_normal(d).astype(np.float32)
        }
        enc_new.advance(state, r)
        cohort = [(r * k + j) % n for j in range(k)]  # strict rotation
        for cid in cohort:
            bundle = enc_new.bundle_for(acked.get(cid))
            bytes_new += bundle.ByteSize()
            acked[cid] = r
        # PR 10 rule: a rotating cohort never has every recipient on the
        # previous broadcast, so every push was self-contained.
        old_bundle, _view = enc_old.encode(state, r, allow_delta=False)
        bytes_old += old_bundle.ByteSize() * k
    return {
        "n_clients": n,
        "k": k,
        "rounds": rounds,
        "tensor_elems": d,
        "codec": codec_spec,
        "sent_bytes_per_recipient_encoding": bytes_new,
        "sent_bytes_selfcontained_pr10": bytes_old,
        "sent_bytes_ratio": round(bytes_old / max(1, bytes_new), 2),
    }


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--single":
        mode, n, fan, rounds = (
            sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
            int(sys.argv[5]),
        )
        print(json.dumps(run_single(mode, n, fan, rounds)))
        return 0

    configs = []
    for mode, n, fan, rounds in MATRIX:
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--single", mode, str(n), str(fan), str(rounds),
        ]
        print(f"== {mode} N={n} fan={fan} rounds={rounds}", file=sys.stderr)
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        out = subprocess.run(
            cmd, capture_output=True, text=True, timeout=1800, env=env,
        )
        if out.returncode != 0:
            print(out.stderr[-2000:], file=sys.stderr)
            raise SystemExit(f"config {mode} N={n} failed")
        configs.append(json.loads(out.stdout.strip().splitlines()[-1]))
        print(json.dumps(configs[-1]), file=sys.stderr)

    by = {(c["mode"], c["n_clients"]): c for c in configs}

    def ratio(mode, key):
        lo, hi = by[(mode, 1_000)][key], by[(mode, 10_000)][key]
        return round(hi / max(lo, 1e-9), 2)

    rotation = rotation_codec_measurement()
    result = {
        "bench": "scale",
        "rev": "r01",
        "host": os.uname().nodename,
        "configs": configs,
        "ratios_10k_over_1k": {
            "cohort_rss": ratio("cohort", "peak_rss_mb"),
            "cohort_bytes_per_round": ratio("cohort", "bytes_per_round"),
            "push_rss": ratio("push", "peak_rss_mb"),
            "push_bytes_per_round": ratio("push", "bytes_per_round"),
            "sync_rss": ratio("sync", "peak_rss_mb"),
            "sync_bytes_per_round": ratio("sync", "bytes_per_round"),
        },
        "rotation_codec": rotation,
        "acceptance": {
            "fixed_fan_rss_flat_1p2x": (
                ratio("cohort", "peak_rss_mb") <= 1.2
                and ratio("push", "peak_rss_mb") <= 1.2
            ),
            "fixed_fan_bytes_flat_1p2x": (
                ratio("cohort", "bytes_per_round") <= 1.2
                and ratio("push", "bytes_per_round") <= 1.2
            ),
            "sync_bytes_grow_5x": (
                ratio("sync", "bytes_per_round") >= 5.0
            ),
            "rotation_ratio_over_2x": (
                rotation["sent_bytes_ratio"] > 2.0
            ),
        },
    }
    # Shared artifact-shape contract: a BENCH_SCALE artifact missing its
    # acceptance/ratio fields must fail HERE, not in a later reader.
    import bench_schema

    bench_schema.require(result, "scale_bench")
    with open(OUT_PATH, "w") as fh:
        json.dump(result, fh, indent=1)
        fh.write("\n")
    print(json.dumps(result["ratios_10k_over_1k"]))
    print(json.dumps(result["acceptance"]))
    print(f"wrote {OUT_PATH}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
