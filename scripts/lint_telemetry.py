#!/usr/bin/env python
"""Telemetry-schema lint — now a shim over the graftlint framework.

The implementation moved to
``gfedntm_tpu/analysis/rules/telemetry.py`` (rule ``telemetry-contract``
/ GL001) when PR 8 folded the standalone script into the repo's
static-analysis suite; run the full suite with
``python -m gfedntm_tpu.analysis`` (or ``scripts/graftlint.py``). This
wrapper keeps the historical entry point working — same checks, same
exit codes (0 = clean, 1 = drift) — by running ONLY the telemetry rule,
without the baseline (telemetry findings are never baselined: the
schema is cheap to update and silence is the failure mode).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, REPO)


def main() -> int:
    from gfedntm_tpu.analysis.core import (
        LintContext,
        collect_default_files,
        load_source,
        run_rules,
    )
    from gfedntm_tpu.analysis.rules.telemetry import TelemetryContractRule

    rule = TelemetryContractRule()
    files = [load_source(p, REPO) for p in collect_default_files(REPO)]
    findings = run_rules([rule], files, LintContext(root=REPO))
    if findings:
        sys.stderr.write("telemetry schema drift:\n")
        for f in findings:
            sys.stderr.write(f.render() + "\n")
        return 1
    scoped = [f for f in files if rule.applies_to(f.rel)]
    events = rule.emitted_events(scoped)
    spans = rule.declared_spans(scoped)
    print(
        f"telemetry lint: {len(events)} distinct events across "
        f"{sum(len(v) for v in events.values())} call sites, all "
        f"registered; {len(spans)} span names cover the trace plane "
        "(full suite: python -m gfedntm_tpu.analysis)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
