#!/usr/bin/env python
"""Telemetry-schema lint: every event the codebase emits must be registered.

Scans ``gfedntm_tpu`` (plus ``bench.py``) for ``<logger>.log("<event>", ...)``
call sites and asserts each event name appears in
``observability.EVENT_SCHEMAS`` — the documented contract the ``summarize``
CLI and the JSONL stream validators run on. An unregistered event would
pass silently in un-validated production loggers and then explode the first
time a test constructs ``MetricsLogger(validate=True)``; this lint moves
that failure to check time.

Exit code 0 = clean; 1 = drift (unregistered events listed on stderr).
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))

#: `<expr>.log("name", ...)` where <expr> ends in a metrics-ish name — the
#: codebase's MetricsLogger handles are `metrics`, `m`, `logger.metrics`,
#: `self.metrics`. Python `logging` handles are `logger`/`self.logger` and
#: use level methods (.info/.warning), never `.log("str")`, so a plain
#: `.log("` with a string literal first arg is a telemetry emission.
_LOG_CALL = re.compile(r"""\.log\(\s*\n?\s*["']([a-z][a-z0-9_]*)["']""")

#: `span(<logger-expr>, "name", ...)` call sites — the span-name vocabulary
#: the trace-merge CLI keys on (observability.TRACE_PLANE_SPANS) must keep
#: existing here, or `trace` would merge streams that can never contain the
#: spans it aligns and parents by.
_SPAN_CALL = re.compile(
    r"""\bspan\(\s*\n?\s*[\w.()\[\]]+\s*,\s*\n?\s*["']([a-z][a-z0-9_]*)["']"""
)

SCAN_ROOTS = ("gfedntm_tpu", "bench.py")


def _scan_paths() -> list[str]:
    paths: list[str] = []
    for root in SCAN_ROOTS:
        full = os.path.join(REPO, root)
        if os.path.isfile(full):
            paths.append(full)
            continue
        for dirpath, _dirs, files in os.walk(full):
            paths.extend(
                os.path.join(dirpath, f) for f in files if f.endswith(".py")
            )
    return sorted(paths)


def _call_sites(pattern: "re.Pattern") -> dict[str, list[str]]:
    """Map of matched name -> list of ``path:line`` sites."""
    sites: dict[str, list[str]] = {}
    for path in _scan_paths():
        text = open(path).read()
        for m in pattern.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            rel = os.path.relpath(path, REPO)
            sites.setdefault(m.group(1), []).append(f"{rel}:{line}")
    return sites


def emitted_events() -> dict[str, list[str]]:
    """Map of event name -> list of ``path:line`` emission sites."""
    return _call_sites(_LOG_CALL)


def declared_spans() -> dict[str, list[str]]:
    """Map of span name -> list of ``path:line`` span() call sites."""
    return _call_sites(_SPAN_CALL)


def main() -> int:
    sys.path.insert(0, REPO)
    from gfedntm_tpu.utils.observability import (
        DATA_PLANE_EVENTS,
        EVENT_SCHEMAS,
        MODEL_QUALITY_EVENTS,
        TRACE_PLANE_SPANS,
    )

    sites = emitted_events()
    if not sites:
        sys.stderr.write("lint_telemetry: found no .log() call sites — "
                         "the scanner regex is probably broken\n")
        return 1
    drift = {
        name: where for name, where in sites.items()
        if name not in EVENT_SCHEMAS
    }
    if drift:
        sys.stderr.write(
            "telemetry schema drift: events emitted but not registered in "
            "observability.EVENT_SCHEMAS:\n"
        )
        for name, where in sorted(drift.items()):
            sys.stderr.write(f"  {name!r}: {', '.join(where)}\n")
        return 1
    # Reverse direction for the data-plane defense AND model-quality
    # events: each must keep at least one emission site AND a schema
    # entry — a refactor that disconnects (or de-registers) the admission
    # gate / guardian / ckpt integrity / quality-monitor telemetry would
    # otherwise pass silently.
    required = DATA_PLANE_EVENTS + MODEL_QUALITY_EVENTS
    unemitted = [e for e in required if e not in sites]
    unregistered = [e for e in required if e not in EVENT_SCHEMAS]
    if unemitted or unregistered:
        sys.stderr.write(
            "data-plane/model-quality telemetry drift: "
            f"events with no .log() call site: {unemitted}; "
            f"events missing from EVENT_SCHEMAS: {unregistered}\n"
        )
        return 1
    spans = declared_spans()
    if not spans:
        sys.stderr.write("lint_telemetry: found no span() call sites — "
                         "the span scanner regex is probably broken\n")
        return 1
    missing = [n for n in TRACE_PLANE_SPANS if n not in spans]
    if missing:
        sys.stderr.write(
            "trace-plane drift: span names the trace-merge CLI relies on "
            f"(observability.TRACE_PLANE_SPANS) have no span() call site: "
            f"{missing}\n"
        )
        return 1
    print(
        f"telemetry lint: {len(sites)} distinct events across "
        f"{sum(len(w) for w in sites.values())} call sites, all "
        f"registered; {len(spans)} span names cover the trace plane's "
        f"{list(TRACE_PLANE_SPANS)}; all {len(DATA_PLANE_EVENTS)} "
        f"data-plane defense + {len(MODEL_QUALITY_EVENTS)} model-quality "
        "events wired"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
