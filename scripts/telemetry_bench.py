#!/usr/bin/env python
"""Fleet-telemetry overhead bench: the BENCH_TELEMETRY artifact (ISSUE 16).

Telemetry shipping piggybacks delta-encoded registry reports on RPCs the
federation already makes, so its cost must be marginal by construction.
This bench runs the same simulated loopback federation (real wire /
codec / pacing / registry planes, stubbed learning) twice — telemetry
shipping ON (every client ships + the server ingests/merges every round)
vs OFF — and compares:

- median round wall-clock (the server's own per-round ``span`` events);
- per-round wire bytes (the loopback byte counter sees the piggybacked
  report bytes exactly where a real transport would).

Acceptance bar (ISSUE 16): both overheads < 3%. Exit 1 when breached.

Usage:
    python scripts/telemetry_bench.py                  # -> BENCH_TELEMETRY_r01.json
    python scripts/telemetry_bench.py --rounds 8 --clients 8 --vocab 2000
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, REPO)

OUT_PATH = os.path.join(REPO, "BENCH_TELEMETRY_r01.json")
OVERHEAD_BOUND = 0.03


def run_config(telemetry: bool, n_clients: int, vocab: int,
               rounds: int) -> dict:
    """One federation run; returns median round seconds + per-round bytes."""
    from gfedntm_tpu.federation.simfleet import make_sim_fleet
    from gfedntm_tpu.utils.observability import MetricsLogger

    server_m = MetricsLogger(validate=True, node="server")
    client_loggers = {
        cid: MetricsLogger(node=f"client{cid}")
        for cid in range(1, n_clients + 1)
    }
    save_dir = tempfile.mkdtemp(prefix="telemetry-bench-")
    t0 = time.perf_counter()
    server, servicers, template = make_sim_fleet(
        n_clients,
        vocab_size=vocab,
        steps=rounds + 2,  # nobody finishes before max_iters ends the run
        pacing_policy="sync",
        max_iters=rounds,
        save_dir=save_dir,
        checkpoint_every=0,
        journal_every=0,
        metrics=server_m,
        client_metrics=(
            (lambda cid: client_loggers[cid]) if telemetry else None
        ),
    )
    assert server.wait_done(timeout=600), "bench federation did not finish"
    wall_s = time.perf_counter() - t0
    server.stop()

    round_s = [
        r["seconds"] for r in server_m.events("span")
        if r.get("name") == "round"
    ]
    counter = server.byte_counter
    fleet_nodes = len(server.fleet.node_snapshots())
    if telemetry:
        assert fleet_nodes >= n_clients, (
            f"telemetry ON but only {fleet_nodes} fleet nodes — the "
            "shipping path is not exercising what this bench measures"
        )
    return {
        "telemetry": telemetry,
        "rounds": int(server.global_iterations),
        "median_round_s": statistics.median(round_s) if round_s else 0.0,
        "bytes_per_round": (
            (counter.sent + counter.recv) / max(1, server.global_iterations)
        ),
        "fleet_nodes": fleet_nodes,
        "wall_s": round(wall_s, 3),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--clients", type=int, default=8)
    # The report cost is fixed per (client, round) — it does not scale
    # with the model — so the vocab sets the round weight the overhead
    # is measured against. 12k is the small end of realistic federated
    # topic-model vocabularies (rounds ~50 ms here); the stub fleet's
    # unloaded ~15 ms rounds would measure the sim's floor, not the
    # plane's marginal cost.
    p.add_argument("--vocab", type=int, default=12_000)
    p.add_argument("--rounds", type=int, default=12)
    p.add_argument("--repeats", type=int, default=2)
    p.add_argument("--out", default=OUT_PATH)
    args = p.parse_args(argv)

    # Best-of-N medians per config, OFF first: scheduler noise only ever
    # inflates a run, so the min is the honest per-round cost, and any
    # JIT/warmup asymmetry lands on (and favors) the OFF side.
    def best(telemetry: bool) -> dict:
        runs = [
            run_config(telemetry, args.clients, args.vocab, args.rounds)
            for _ in range(max(1, args.repeats))
        ]
        return min(runs, key=lambda r: r["median_round_s"])

    off = best(False)
    on = best(True)

    def frac(a, b):
        return (a - b) / b if b else 0.0

    result = {
        "bench": "telemetry_overhead",
        "clients": args.clients,
        "vocab": args.vocab,
        "bound": OVERHEAD_BOUND,
        "off": off,
        "on": on,
        "overhead_round_s": round(
            frac(on["median_round_s"], off["median_round_s"]), 4
        ),
        "overhead_bytes": round(
            frac(on["bytes_per_round"], off["bytes_per_round"]), 4
        ),
    }
    result["ok"] = (
        result["overhead_round_s"] < OVERHEAD_BOUND
        and result["overhead_bytes"] < OVERHEAD_BOUND
    )
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(result, fh, indent=1)
            fh.write("\n")
    if not result["ok"]:
        print(
            f"telemetry overhead exceeds the {OVERHEAD_BOUND:.0%} bound: "
            f"round_s {result['overhead_round_s']:+.2%}, "
            f"bytes {result['overhead_bytes']:+.2%}", file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
