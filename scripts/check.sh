#!/usr/bin/env bash
# Repo check gate: byte-compile everything, run the graftlint static-
# analysis suite (telemetry contract, precision pins, donation safety,
# lock discipline, exception hygiene — README "Static analysis"), verify
# proto codegen drift, and run the tier-1 test command from ROADMAP.md.
# Run from anywhere:
#   scripts/check.sh [extra pytest args...]
#
# Environment:
#   SKIP_TESTS=1   fast pre-commit loop: compileall + graftlint +
#                  proto-drift only (~30 s — no pytest collection)
set -o pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"

echo "== compileall =="
# scripts/, tests/, and the entry points compile too: a syntax error in
# a script or test must fail here, not ship silently until tier-1.
python -m compileall -q gfedntm_tpu scripts tests bench.py main.py || exit 1

echo "== graftlint (static analysis) =="
# Fails on any NEW finding (scripts/lint_baseline.json pins the reviewed
# exceptions, each with a justification). Includes the telemetry-schema
# lint that used to be a standalone stage (scripts/lint_telemetry.py is
# now a shim over the same rule).
python -m gfedntm_tpu.analysis || exit 1

echo "== proto codegen drift =="
# gen_protos is idempotent; if running it CHANGES the pb2, the checked-in
# module does not match the declared schema.
PB2=gfedntm_tpu/federation/protos/federated_pb2.py
before=$(sha256sum "$PB2")
python scripts/gen_protos.py >/dev/null || exit 1
after=$(sha256sum "$PB2")
if [ "$before" != "$after" ]; then
    echo "federated_pb2.py was stale: commit the scripts/gen_protos.py output" >&2
    exit 1
fi

if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo "== tests skipped (SKIP_TESTS=1) =="
    exit 0
fi

# Observability-plane, data-plane, model-quality, and analysis test
# modules must at least collect (import-time breakage surfaces in the
# fast loop too; the full run happens in tier-1).
echo "== observability/data-plane/quality/analysis test modules collect =="
env JAX_PLATFORMS=cpu python -m pytest --collect-only -q \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    tests/test_trace_plane.py tests/test_ops_endpoint.py \
    tests/test_data_plane.py tests/test_device_agg.py \
    tests/test_metrics.py tests/test_quality_plane.py \
    tests/test_analysis.py tests/test_pacing.py \
    tests/test_survival.py tests/test_scaleout.py \
    tests/test_multichip.py tests/test_serving.py \
    tests/test_scenarios.py tests/test_privacy.py \
    tests/test_fleet_telemetry.py tests/test_slo.py \
    tests/test_forensics.py \
    tests/chaos/test_process_chaos.py \
    >/dev/null || exit 1

# SLO CLI gate (README "Fleet telemetry & SLOs"): the offline `slo`
# subcommand must pass a known-good stream (exit 0) and fail a seeded
# violation (exit 1) — the CI-gate contract itself is what's checked,
# from synthetic fixtures generated inline so the stage needs no
# checked-in artifacts.
echo "== slo CLI gate =="
SLO_TMP=$(mktemp -d)
env JAX_PLATFORMS=cpu python - "$SLO_TMP" <<'PY' || exit 1
import json, sys
tmp = sys.argv[1]
def stream(path, errors):
    with open(path, "w") as fh:
        for t, err in enumerate(errors):
            fh.write(json.dumps({
                "event": "metrics_snapshot", "time": 1000.0 + t,
                "node": "server",
                "metrics": {"serving_errors":
                            {"type": "counter", "value": float(err)}},
            }) + "\n")
stream(f"{tmp}/good.jsonl", [0, 0, 0, 0])
stream(f"{tmp}/bad.jsonl", [0, 5, 9, 12])
with open(f"{tmp}/slo.json", "w") as fh:
    json.dump([{"name": "no-serve-errors", "metric": "serving_errors",
                "agg": "value", "op": "<=", "threshold": 0.0}], fh)
PY
env JAX_PLATFORMS=cpu python -m gfedntm_tpu.cli slo \
    --slo "$SLO_TMP/slo.json" "$SLO_TMP/good.jsonl" || exit 1
if env JAX_PLATFORMS=cpu python -m gfedntm_tpu.cli slo \
    --slo "$SLO_TMP/slo.json" "$SLO_TMP/bad.jsonl" >/dev/null 2>&1; then
    echo "slo CLI failed to flag a seeded SLO violation" >&2
    exit 1
fi
rm -rf "$SLO_TMP"

# Privacy CLI gate (README "Differential privacy & posterior sampling"):
# the offline `privacy` subcommand must pass a budget-respecting ledger
# (exit 0) and fail a budget-exceeding one (exit 1) — same inline-
# fixture pattern as the slo gate above.
echo "== privacy CLI gate =="
DP_TMP=$(mktemp -d)
env JAX_PLATFORMS=cpu python - "$DP_TMP" <<'PY' || exit 1
import json, sys
tmp = sys.argv[1]
def ledger(path, eps_series, budget):
    with open(path, "w") as fh:
        for r, eps in enumerate(eps_series):
            fh.write(json.dumps({
                "event": "privacy_budget", "time": 1000.0 + r,
                "node": "server", "round": r, "eps": eps,
                "delta": 1e-5, "steps": r + 1, "q": 1.0,
                "sigma": 2.0, "mode": "server", "budget": budget,
            }) + "\n")
ledger(f"{tmp}/good.jsonl", [0.4, 0.8, 1.1], budget=3.0)
ledger(f"{tmp}/bad.jsonl", [1.4, 2.6, 3.9], budget=3.0)
PY
env JAX_PLATFORMS=cpu python -m gfedntm_tpu.cli privacy \
    "$DP_TMP/good.jsonl" || exit 1
if env JAX_PLATFORMS=cpu python -m gfedntm_tpu.cli privacy \
    "$DP_TMP/bad.jsonl" >/dev/null 2>&1; then
    echo "privacy CLI failed to flag a seeded budget violation" >&2
    exit 1
fi
rm -rf "$DP_TMP"

# Incident CLI gate (README "Incident forensics"): `incident
# --assert-no-incidents` must pass an empty bundle directory (exit 0)
# and fail once a bundle exists (exit 1). The seeded bundle is produced
# by the REAL capture path — a trigger event through a recorder-armed
# MetricsLogger — so the gate also proves trigger -> atomic bundle
# end-to-end, same inline-fixture pattern as the slo gate above.
echo "== incident CLI gate =="
INC_TMP=$(mktemp -d)
mkdir -p "$INC_TMP/incidents"
env JAX_PLATFORMS=cpu python -m gfedntm_tpu.cli incident \
    "$INC_TMP/incidents" --assert-no-incidents || exit 1
env JAX_PLATFORMS=cpu python - "$INC_TMP" <<'PY' || exit 1
import sys
from gfedntm_tpu.utils import flightrec
from gfedntm_tpu.utils.observability import MetricsLogger

tmp = sys.argv[1]
m = MetricsLogger(keep_records=True, node="server")
rec = flightrec.FlightRecorder()
m.recorder = rec
flightrec.IncidentTrigger(rec, f"{tmp}/incidents", metrics=m, node="server")
m.log("checkpoint", round=1)
m.log("divergence_rollback", round=2, reason="seeded-gate-fixture")
m.close()
PY
if env JAX_PLATFORMS=cpu python -m gfedntm_tpu.cli incident \
    "$INC_TMP/incidents" --assert-no-incidents >/dev/null 2>&1; then
    echo "incident CLI failed to flag a seeded postmortem bundle" >&2
    exit 1
fi
env JAX_PLATFORMS=cpu python -m gfedntm_tpu.cli incident \
    "$INC_TMP/incidents" >/dev/null || exit 1
rm -rf "$INC_TMP"

if [ "${SCENARIO:-0}" = "1" ]; then
    # Scenario-matrix smoke (README "Scenario matrix"): two fast cells
    # end-to-end through the real in-process federation — one clean
    # non-IID cell and one crash-persona cell exercising zero-flag
    # autorecovery — with every degradation contract asserted. The full
    # >= 12-cell matrix is the BENCH_SCENARIO artifact run:
    #   python -m gfedntm_tpu.cli scenarios --out BENCH_SCENARIO_rNN.json
    echo "== scenario-matrix smoke (SCENARIO=1) =="
    env JAX_PLATFORMS=cpu python -m gfedntm_tpu.cli scenarios --fast \
        --cells dir01-sync-fedavg,iid-crash-sync \
        --workdir "$(mktemp -d)" || exit 1
fi

if [ "${MULTICHIP:-0}" = "1" ]; then
    # Fast multi-chip gate (README "Multi-chip training & bench
    # interpretation"): the forced-8-device sharded-vs-single-device
    # parity tests plus the dryrun_multichip graft entry, so the
    # multi-chip paths stay drivable without an accelerator.
    echo "== multi-chip parity + graft dryrun (MULTICHIP=1) =="
    env JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/test_multichip.py -q -m 'not slow' \
        -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
    env JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -c "
import jax
jax.config.update('jax_platforms', 'cpu')
import __graft_entry__ as g
g.dryrun_multichip(8)
print('dryrun_multichip(8) OK')
" || exit 1
fi

if [ "${CHAOS:-0}" = "1" ]; then
    # Process-level chaos suite (README "Crash recovery & sessions",
    # "Survivable hierarchy"): spawns the real CLI as subprocesses and
    # SIGKILLs the server mid-round / clients mid-step / one relay of a
    # two-tier hierarchy mid-round. Slow-marked, excluded from tier-1;
    # opt in with CHAOS=1.
    echo "== process-level chaos suite (CHAOS=1) =="
    env JAX_PLATFORMS=cpu python -m pytest tests/chaos -q -m slow \
        -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
fi

echo "== tier-1 tests (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly "$@" 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
