#!/usr/bin/env python
"""Regenerate ``federated_pb2.py`` without protoc.

The image ships the protobuf *runtime* but no ``protoc`` / ``grpcio-tools``
binary, so schema evolution works at the descriptor level: this script
parses the serialized ``FileDescriptorProto`` already embedded in the
checked-in ``federated_pb2.py``, adds any missing fields declared in
``WANTED_FIELDS`` (append-only, proto3-compatible evolution: new optional
scalar fields with fresh tags), and rewrites the module. Idempotent — run
it again and it reports "up to date".

Keep ``federated.proto`` (the human-readable schema) in sync by hand; it is
documentation plus the source of truth for anyone regenerating with a real
protoc elsewhere.
"""

from __future__ import annotations

import os
import re
import sys

from google.protobuf import descriptor_pb2

PB2_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir,
    "gfedntm_tpu", "federation", "protos", "federated_pb2.py",
)

F = descriptor_pb2.FieldDescriptorProto

#: message -> [(field_name, tag, type)] that must exist (added if missing).
WANTED_FIELDS: dict[str, list[tuple[str, int, int]]] = {
    # Wire-compression support: per-record transform id ("raw", "topk", ...),
    # auxiliary payload (top-k indices), and the on-wire dtype when the
    # payload is quantized below the logical `dtype`.
    "TensorRecord": [
        ("codec", 5, F.TYPE_STRING),
        ("aux", 6, F.TYPE_BYTES),
        ("wire_dtype", 7, F.TYPE_STRING),
    ],
    # Delta encoding: 1 + the round whose broadcast aggregate is the
    # reference (proto3 cannot distinguish 0 from unset, so the wire
    # carries round+1; 0 = self-contained bundle).
    "TensorBundle": [("ref_round", 2, F.TYPE_INT64)],
    # Codec negotiation at join time (mixed fleets must fail loudly), and
    # durable client sessions (README "Crash recovery & sessions"): the
    # server mints `session_token` per client in its GetGlobalSetup reply;
    # a client whose connection dies presents it in ReadyForTraining to
    # re-enter the federation as the SAME live process (registry record,
    # straggler EWMA, and push-ack/codec posture restored) instead of
    # being treated as a fresh rejoin.
    # `telemetry` (README "Fleet telemetry & SLOs"): a rejoining client
    # piggybacks a FULL delta-encoded MetricRegistry report, so the
    # server's FleetRegistry resynchronizes the node's series in the same
    # RPC that restores its session — no extra round-trips, best-effort
    # (an empty field costs nothing on the wire).
    # `recovered` rides a token reconnect from a process that crashed and
    # restored itself from its own journal (a respawned relay): the
    # session is the SAME — weight, straggler EWMA, registry identity
    # survive — but the presenter's wire-codec state died with the old
    # process, so the receiver must drop its per-recipient push-ack /
    # delta-reference posture and send the next broadcast self-contained.
    "JoinRequest": [
        ("codec_id", 3, F.TYPE_STRING),
        ("session_token", 4, F.TYPE_STRING),
        ("telemetry", 5, F.TYPE_BYTES),
        ("recovered", 6, F.TYPE_BOOL),
    ],
    # Pacing negotiation (README "Hierarchical federation & wire
    # efficiency"): the server advertises its round pacing policy
    # (`pacing_id`, e.g. "push:8") and the per-round local-step budget
    # (`local_steps`) in the consensus reply, so a client knows whether
    # to wait for server polls (sync/cohort/async) or to stream
    # PushUpdate rounds on its own clock (push pacing).
    "GlobalSetup": [
        ("codec_id", 6, F.TYPE_STRING),
        ("session_token", 7, F.TYPE_STRING),
        ("pacing_id", 8, F.TYPE_STRING),
        ("local_steps", 9, F.TYPE_INT64),
    ],
    # The round a push belongs to (clients tag their delta references with
    # it; the stop broadcast leaves it 0). `reset_session` rides a
    # divergence-rollback re-broadcast: the receiving client must drop its
    # wire-codec session state (delta references AND error-feedback
    # residuals) before applying, so no mass from the discarded diverged
    # trajectory leaks into post-rollback rounds.
    # `capture_token` (README "Incident forensics"): a root-side incident
    # id soliciting a flight-record snapshot from the receiving node —
    # the client answers on its next StepReply (poll reply or
    # client-initiated PushUpdate, which reuses the message), deduped by
    # token so a re-broadcast token costs nothing. Rides the replies the
    # push path already sends, same best-effort discipline as telemetry.
    "Aggregate": [
        ("round", 3, F.TYPE_INT64),
        ("reset_session", 4, F.TYPE_BOOL),
        ("capture_token", 5, F.TYPE_STRING),
    ],
    # Pacing / staleness tags (README "Federation pacing"): the server
    # stamps each poll with its aggregation counter at dispatch
    # (`broadcast_round`), and the client reports 1 + the round tag of
    # the last aggregate it actually applied (`base_round`, 0 = still on
    # the replicated init). Under async pacing the difference is the
    # update's staleness, which discounts its aggregation weight.
    #
    # `seq` (README "Crash recovery & sessions") is the server-minted
    # per-delivery sequence number making TrainStep idempotent: the
    # client caches its last (seq, reply) and answers a replayed
    # delivery — a retry after a timed-out-but-delivered call — with the
    # cached snapshot instead of running more local steps; the reply
    # echoes the seq so the server can drop duplicate StepReplies before
    # they double-count in the average.
    # `capture_token` (README "Incident forensics"): same solicited
    # flight-record pull riding the polls sync/cohort/async pacing
    # already sends; a relay forwards the token on its downstream
    # fan-out and pre-bundles its members' snapshots with its own, so
    # the upstream cost stays O(relays).
    "StepRequest": [
        ("broadcast_round", 3, F.TYPE_INT64),
        ("seq", 4, F.TYPE_INT64),
        ("capture_token", 5, F.TYPE_STRING),
    ],
    # `session_token` authenticates client-initiated PushUpdate rounds
    # (push pacing): the server only buffers an update whose token matches
    # the member's current durable session — a stale process's pushes are
    # turned away instead of entering the average.
    # `telemetry` piggybacks the node's delta-encoded MetricRegistry
    # report on replies it already sends (polls AND client-initiated
    # pushes reuse this message) — the fleet telemetry plane's shipping
    # path (README "Fleet telemetry & SLOs"). Loss-tolerant: a dropped
    # reply drops its deltas, and the shipper's periodic full report
    # heals the receiver.
    # `flightrec` (README "Incident forensics") answers a solicited
    # capture_token: a zlib-compressed JSON list of node flight-record
    # bundles (a list so a relay can pre-bundle its members' snapshots
    # with its own into ONE upstream blob). Best-effort and
    # loss-tolerant like `telemetry`: a dropped reply drops its
    # snapshot, and the token re-rides the next exchange.
    "StepReply": [
        ("base_round", 8, F.TYPE_INT64),
        ("seq", 9, F.TYPE_INT64),
        ("session_token", 10, F.TYPE_STRING),
        ("telemetry", 11, F.TYPE_BYTES),
        ("flightrec", 12, F.TYPE_BYTES),
    ],
}

#: message -> [(field_name, tag, type, type_name)] for messages that must
#: EXIST (added whole if missing — append-only schema evolution for brand
#: new workloads). `type_name` is the fully-qualified message type for
#: TYPE_MESSAGE fields ("" for scalars).
WANTED_MESSAGES: dict[str, list[tuple[str, int, int, str]]] = {
    # Serving plane (README "Serving"): one doc->topic inference batch.
    # `bow` is a TensorBundle holding a single dense [B, V] float32 "bow"
    # record (the same tensor transport training uses); `request_id` is a
    # client-chosen correlation id echoed in the reply.
    "InferRequest": [
        ("bow", 1, F.TYPE_MESSAGE, ".gfedntm.TensorBundle"),
        ("request_id", 2, F.TYPE_INT64, ""),
    ],
    # `theta` carries one dense [B, K] "theta" record; `model_round` names
    # the federation round of the model that answered (observability for
    # hot-swap: a client can see which published model served it).
    "InferReply": [
        ("theta", 1, F.TYPE_MESSAGE, ".gfedntm.TensorBundle"),
        ("model_round", 2, F.TYPE_INT64, ""),
        ("request_id", 3, F.TYPE_INT64, ""),
    ],
}

TEMPLATE = '''# -*- coding: utf-8 -*-
# Generated by scripts/gen_protos.py (descriptor-level evolution; the image
# has no protoc).  DO NOT EDIT BY HAND — edit WANTED_FIELDS there and rerun.
# source: federated.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({serialized!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'federated_pb2', globals())
# @@protoc_insertion_point(module_scope)
'''


def main() -> int:
    src = open(PB2_PATH).read()
    m = re.search(r"AddSerializedFile\((b'(?:[^'\\]|\\.)*')\)", src, re.S)
    if m is None:
        raise SystemExit(f"could not find serialized descriptor in {PB2_PATH}")
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.ParseFromString(eval(m.group(1)))  # noqa: S307 — our own literal

    changed = False
    by_name = {msg.name: msg for msg in fdp.message_type}
    for msg_name, fields in WANTED_MESSAGES.items():
        if msg_name in by_name:
            continue
        msg = fdp.message_type.add(name=msg_name)
        for name, tag, ftype, type_name in fields:
            field = msg.field.add(
                name=name, number=tag, type=ftype, label=F.LABEL_OPTIONAL,
            )
            if type_name:
                field.type_name = type_name
        by_name[msg_name] = msg
        changed = True
    for msg_name, fields in WANTED_FIELDS.items():
        msg = by_name[msg_name]
        have = {f.name for f in msg.field}
        tags = {f.number for f in msg.field}
        for name, tag, ftype in fields:
            if name in have:
                continue
            if tag in tags:
                raise SystemExit(
                    f"{msg_name}: tag {tag} already used; pick a fresh one"
                )
            msg.field.add(
                name=name, number=tag, type=ftype,
                label=F.LABEL_OPTIONAL,
            )
            changed = True
    if not changed:
        print("federated_pb2.py up to date")
        return 0
    with open(PB2_PATH, "w") as fh:
        fh.write(TEMPLATE.format(serialized=fdp.SerializeToString()))
    print(f"rewrote {PB2_PATH}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
