"""BENCH-JSON schema validator shared by every artifact emitter.

One place that says what each bench artifact line/object must carry, so
the fields downstream readers key on (the trajectory reviewer, summarize,
the scale/pacing acceptance checks) cannot silently drift when an emitter
is refactored — exactly what happened to the r03-r05 run-phase evidence.

Used by ``bench.py`` (main summary + partial summaries), by
``scripts/agg_microbench.py`` (per-row metrics), and by
``scripts/scale_bench.py`` (the BENCH_SCALE artifact). ``validate`` is
pure and returns problem strings; emitters that must never crash
(bench.py) report them in-band as ``schema_errors``, while dev tools
(the scripts) raise via :func:`require`.
"""

from __future__ import annotations

#: kind -> required top-level fields. Presence-only by design: value
#: domains are the emitters' business, the SHAPE contract is ours.
SCHEMAS: dict[str, tuple[str, ...]] = {
    # bench.py's one-line summary (any provenance: live / cached /
    # degraded / partial).
    "bench": ("metric", "value", "unit", "vs_baseline", "backend"),
    # The partial summary StageLog flushes after every completed stage.
    "bench_partial": (
        "metric", "value", "unit", "backend", "partial", "run_stages",
    ),
    # scripts/agg_microbench.py per-row JSON lines, keyed by row metric.
    "agg_estimator_wall_ms": (
        "metric", "estimator", "backend", "n_clients", "d", "wall_ms",
    ),
    "agg_growth": ("metric", "estimator", "n_lo", "n_hi", "d"),
    "pacing_round_wall_ms": (
        "metric", "estimator", "n_clients", "cohort_spec", "d", "wall_ms",
    ),
    "pacing_cost_growth": (
        "metric", "estimator", "cohort_spec", "n_lo", "n_hi", "growth",
    ),
    # scripts/scale_bench.py's BENCH_SCALE artifact object.
    "scale_bench": (
        "bench", "rev", "configs", "ratios_10k_over_1k", "acceptance",
    ),
    # scripts/serve_bench.py's BENCH_SERVE artifact object (README
    # "Serving"): sustained docs/s under closed-loop load at a fixed p99
    # target, the hot-swap audit (swaps + zero failed in-flight
    # requests), and the per-second series reproduced from JSONL.
    "serve_bench": (
        "bench", "rev", "backend", "target_p99_ms", "sustained_docs_per_s",
        "qps", "p50_ms", "p99_ms", "swaps", "failures", "series",
        "acceptance",
    ),
    # gfedntm_tpu/scenarios per-cell line (README "Scenario matrix"):
    # one real federation run under composed data/fault/policy personas,
    # with its degradation-contract verdicts.
    "scenario": (
        "metric", "cell", "workload", "data_persona", "fault_persona",
        "pacing", "aggregator", "npmi", "baseline_npmi", "npmi_tol",
        "contracts", "ok", "seconds",
    ),
    # The BENCH_SCENARIO artifact object: every cell's line plus the
    # acceptance flags (>= 12 cells, all contracts green, the
    # dirichlet x crash x cohort headline cell present and green).
    "scenario_bench": ("bench", "rev", "cells", "acceptance"),
    # scripts/dp_bench.py's BENCH_DP artifact object (README
    # "Differential privacy & posterior sampling"): per-round wall-clock
    # overhead of the server noise path (noise-on vs noise-off twins of
    # the same aggregation) and device-vs-host noise-generation timing.
    "dp_bench": (
        "bench", "rev", "backend", "rounds", "noiseless_round_ms",
        "noised_round_ms", "overhead_pct", "noise_gen", "acceptance",
    ),
    # scripts/forensics_bench.py's BENCH_FORENSICS artifact object
    # (README "Incident forensics"): round wall-clock with the flight
    # recorder armed vs absent, plus the capture path's latency and
    # bundle size at full ring depth.
    "forensics_bench": (
        "bench", "rev", "backend", "clients", "rounds", "bound", "off",
        "on", "overhead_round_s", "capture", "acceptance",
    ),
}

#: Fields a bench summary must ALSO carry when the named condition key is
#: present/truthy: an abandoned accelerator attempt must ship evidence.
CONDITIONAL: dict[str, dict[str, tuple[str, ...]]] = {
    "bench": {
        "accel_timeout_phase": ("accel_attempts",),
        "partial": ("run_stages",),
    },
}


def validate(obj: dict, kind: str = "bench") -> list[str]:
    """Problems with ``obj`` under the ``kind`` schema ([] = valid)."""
    if kind not in SCHEMAS:
        return [f"unknown bench schema kind {kind!r}"]
    if not isinstance(obj, dict):
        return [f"{kind}: expected a JSON object, got {type(obj).__name__}"]
    problems = [
        f"{kind}: missing required field {field!r}"
        for field in SCHEMAS[kind]
        if field not in obj
    ]
    for trigger, extras in CONDITIONAL.get(kind, {}).items():
        if obj.get(trigger):
            problems.extend(
                f"{kind}: {trigger!r} present but required companion "
                f"{field!r} missing"
                for field in extras
                if field not in obj
            )
    return problems


def validate_row(row: dict) -> list[str]:
    """Validate a metric-keyed JSON line (agg_microbench rows) against
    the schema its own ``metric`` field names."""
    metric = row.get("metric")
    if metric not in SCHEMAS:
        return [f"row metric {metric!r} has no registered schema"]
    return validate(row, metric)


def require(obj: dict, kind: str = "bench") -> dict:
    """Raise ``ValueError`` on schema problems; returns ``obj`` so
    emitters can validate inline at the emission site."""
    problems = validate(obj, kind)
    if problems:
        raise ValueError(
            "bench artifact schema violation: " + "; ".join(problems)
        )
    return obj
