#!/usr/bin/env python
"""Aggregation microbench: estimator wall-clock vs client count N.

The device-resident aggregation path (ISSUE 6, README "Device-resident
aggregation") exists to make robust-aggregation cost flat as the cohort
grows: the numpy path loops per key and pays O(N·D) host arithmetic
(plus an O(N log N · D) sort for the order statistics), while the device
path stacks once and runs per-coordinate work data-parallel over the
sharded plane. This script makes that claim measurable in the bench
trajectory: for each estimator and backend it times ONE aggregate's mean
stage at fixed parameter size D while N sweeps 4 → 32, and emits JSON
lines (one per measurement plus one growth-summary line per estimator) —
the acceptance check is ``device_growth < numpy_growth`` at N 4→32
(``"sublinear_vs_numpy": true``).

Timing protocol: pairs are built once per N; the device path's one-time
stack + transfer is reported separately (``stack_ms``) from the estimate
wall-clock (the per-round recurring cost is stack + estimate; the stack
is one flatten+concat per client and scales trivially). The first device
call per (estimator, N) shape is a jit compile and is excluded by a
warmup call; each measurement is best-of ``--repeats``.

Run on the test mesh (no accelerator needed):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python scripts/agg_microbench.py

On a TPU host, run it bare: the engine meshes over the real chips.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench_schema  # noqa: E402  (sibling module; scripts/ is sys.path[0])


def _emit(row: dict) -> None:
    """Print one JSON metric line, schema-checked at the emission site
    (scripts/bench_schema.py) so artifact fields can't silently drift."""
    problems = bench_schema.validate_row(row)
    if problems:
        raise SystemExit("agg_microbench schema drift: " + "; ".join(problems))
    print(json.dumps(row), flush=True)


def _build_pairs(n: int, d: int, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    # Realistic key structure: one dominant matrix + two small vectors —
    # the numpy path pays its per-key Python/loop overhead, the plane
    # flattens them all into one [N, D] array.
    d_main = d - 2 * 64
    template = {
        "beta": np.zeros((d_main,), np.float32),
        "mu": np.zeros((64,), np.float32),
        "sigma": np.zeros((64,), np.float32),
    }
    pairs = [
        (
            float(rng.integers(1, 100)),
            {
                k: rng.normal(size=v.shape).astype(np.float32)
                for k, v in template.items()
            },
        )
        for _ in range(n)
    ]
    return template, pairs


def _best_of(fn, repeats: int) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def run_pacing_sweep(args) -> None:
    """Pacing scale sweep (ISSUE 9): for each (population N, cohort K)
    cell, time ONE aggregation's data-plane cost — the admission gate
    pass plus the estimator's mean stage — over a seeded K-of-N cohort
    sample. Non-participants cost nothing (no decode, no gate slot, no
    plane row), so the wall-clock of a cell must track K, not N; the
    summary line per (estimator, K) reports the N-growth ratio
    (``cohort_cost_growth``), which stays ~1 for fixed K while the
    ``all`` column grows with N — the scale claim, measured."""
    import numpy as np

    from gfedntm_tpu.federation.aggregation import make_estimator
    from gfedntm_tpu.federation.pacing import staleness_discount
    from gfedntm_tpu.federation.sanitize import UpdateGate

    ns = [int(x) for x in args.sweep_populations.split(",") if x]
    ks = [k.strip() for k in args.sweep_cohorts.split(",") if k.strip()]
    wall: dict[tuple[str, str, int], float] = {}
    for spec in [s.strip() for s in args.estimators.split(",") if s.strip()]:
        est = make_estimator(spec)
        for n in ns:
            template, pairs = _build_pairs(n, args.d, seed=n)
            zeros = {k: np.zeros_like(v) for k, v in template.items()}
            for k_spec in ks:
                k = n if k_spec == "all" else min(int(k_spec), n)
                rng = np.random.default_rng((0, n, k))
                picked = rng.choice(n, size=k, replace=False)
                # Staleness-discounted candidate weights, exactly as the
                # async engine hands them to the gate.
                cohort = [
                    (int(i), pairs[i][0] * staleness_discount(0, 0.5),
                     pairs[i][1])
                    for i in sorted(int(x) for x in picked)
                ]
                gate = UpdateGate(mad_k=4.0)
                gate.set_template(template)

                def run_cell():
                    result = gate.admit_round(cohort, zeros, 0)
                    est([(w, s) for _c, w, s in result.accepted])

                run_cell()  # warm allocators / caches
                ms = _best_of(run_cell, args.repeats)
                wall[(spec, k_spec, n)] = ms
                _emit({
                    "metric": "pacing_round_wall_ms", "estimator": spec,
                    "n_clients": n, "cohort": k, "cohort_spec": k_spec,
                    "d": args.d, "wall_ms": round(ms, 3),
                })
    # Growth summary: for each (estimator, K) the wall-clock ratio from
    # the smallest to the largest population. Fixed-K rows must stay ~1
    # (cost tracks the cohort); the 'all' row is the sync barrier and
    # grows with N.
    lo, hi = min(ns), max(ns)
    for (spec, k_spec) in sorted({(s, k) for s, k, _n in wall}):
        a, b = wall.get((spec, k_spec, lo)), wall.get((spec, k_spec, hi))
        if not (a and b):
            continue
        row = {
            "metric": "pacing_cost_growth", "estimator": spec,
            "cohort_spec": k_spec, "n_lo": lo, "n_hi": hi,
            "growth": round(b / a, 3), "d": args.d,
        }
        if k_spec != "all":
            row["tracks_cohort"] = row["growth"] < 2.0
        _emit(row)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--d", type=int, default=262_144,
                    help="flattened parameter count (fixed across N)")
    ap.add_argument("--clients", default="4,8,16,32",
                    help="comma-separated cohort sizes")
    ap.add_argument("--estimators",
                    default="mean,trimmed_mean:0.2,median,krum:1")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--backends", default="numpy,device",
                    help="comma subset of numpy,device")
    ap.add_argument("--pacing-sweep", action="store_true",
                    dest="pacing_sweep",
                    help="scale sweep: time one round's data-plane cost "
                         "(gate admission + estimator) at cohort size K "
                         "sampled from population N, for N in "
                         "--sweep-populations x K in --sweep-cohorts — "
                         "the per-round cost must track K, not N")
    ap.add_argument("--sweep-populations", default="16,64,128",
                    dest="sweep_populations")
    ap.add_argument("--sweep-cohorts", default="4,8,all",
                    dest="sweep_cohorts",
                    help="cohort sizes; 'all' = the full population "
                         "(the sync barrier's data-plane cost)")
    args = ap.parse_args()

    if args.pacing_sweep:
        run_pacing_sweep(args)
        return

    import numpy as np

    from gfedntm_tpu.federation.aggregation import make_estimator
    from gfedntm_tpu.federation.device_agg import (
        DeviceAggEngine,
        FlatPlane,
        stack_round,
    )

    ns = [int(x) for x in args.clients.split(",") if x]
    backends = [b.strip() for b in args.backends.split(",") if b.strip()]
    engine = DeviceAggEngine() if "device" in backends else None
    if engine is not None:
        import jax

        sys.stderr.write(
            f"agg_microbench: device backend = {jax.default_backend()} "
            f"x{engine.n_shards}\n"
        )

    wall: dict[tuple[str, str, int], float] = {}
    for spec in args.estimators.split(","):
        spec = spec.strip()
        for n in ns:
            template, pairs = _build_pairs(n, args.d, seed=n)
            if "numpy" in backends:
                est = make_estimator(spec)
                est(pairs)  # warm caches/allocators
                ms = _best_of(lambda: est(pairs), args.repeats)
                wall[(spec, "numpy", n)] = ms
                _emit({
                    "metric": "agg_estimator_wall_ms", "estimator": spec,
                    "backend": "numpy", "n_clients": n, "d": args.d,
                    "wall_ms": round(ms, 3),
                })
            if engine is not None:
                est = make_estimator(spec)
                plane = FlatPlane(template)
                t0 = time.perf_counter()
                sr = stack_round(engine, plane, pairs)
                import jax

                jax.block_until_ready(sr.mat)
                stack_ms = (time.perf_counter() - t0) * 1e3

                def run_dev():
                    out = est(sr)
                    # host materialization is part of the round cost
                    for v in out.values():
                        np.asarray(v)

                run_dev()  # jit compile at this (n, d) shape
                ms = _best_of(run_dev, args.repeats)
                wall[(spec, "device", n)] = ms
                _emit({
                    "metric": "agg_estimator_wall_ms", "estimator": spec,
                    "backend": "device", "n_clients": n, "d": args.d,
                    "wall_ms": round(ms, 3),
                    "stack_ms": round(stack_ms, 3),
                })

    # Growth summary: wall-clock ratio from the smallest to the largest N
    # per (estimator, backend); the device path earns its keep when its
    # ratio is below the numpy path's.
    lo, hi = min(ns), max(ns)
    for spec in [s.strip() for s in args.estimators.split(",")]:
        row = {
            "metric": "agg_growth", "estimator": spec,
            "n_lo": lo, "n_hi": hi, "d": args.d,
        }
        for backend in backends:
            a, b = wall.get((spec, backend, lo)), wall.get(
                (spec, backend, hi)
            )
            if a and b:
                row[f"{backend}_growth"] = round(b / a, 3)
        if "numpy_growth" in row and "device_growth" in row:
            row["sublinear_vs_numpy"] = (
                row["device_growth"] < row["numpy_growth"]
            )
        _emit(row)


if __name__ == "__main__":
    main()
