#!/usr/bin/env python
"""Serving-plane bench: the BENCH_SERVE artifact (README "Serving").

End-to-end, all real planes: a gRPC federation (server + N clients,
journaling every pushed round) trains while a :class:`ServingPlane`
watches its ``save_dir``, hot-swapping each newly published round, and a
closed-loop saturating load generator drives the gRPC ``Infer`` endpoint
the whole time. The artifact reports **sustained docs/s at a fixed p99
target** plus the hot-swap audit:

- ``failures`` must be 0 — the atomic-swap contract is that no in-flight
  request is ever dropped or torn, including across swaps;
- ``swaps`` (distinct model rounds observed BY THE LOAD ITSELF, minus
  one) must be >= 2 — the load provably rode through live model swaps;
- the per-second ``series`` is rebuilt from the telemetry JSONL
  (``serve_load_window`` events), so the artifact is reproducible from
  the stream alone.

Usage:
    python scripts/serve_bench.py                    # -> BENCH_SERVE_r01.json
    python scripts/serve_bench.py --duration 20 --concurrency 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench_schema  # noqa: E402

OUT_PATH = os.path.join(REPO, "BENCH_SERVE_r01.json")

MODEL_KWARGS = dict(
    n_components=4, hidden_sizes=(16,), batch_size=8, num_epochs=40, seed=0,
)


def _corpora(n_clients: int, docs: int, vocab: int, seed: int = 0):
    import numpy as np

    from gfedntm_tpu.data.loaders import RawCorpus

    rng = np.random.default_rng(seed)
    words = [f"tok{i:03d}" for i in range(vocab)]
    return [
        RawCorpus(documents=[
            " ".join(rng.choice(words, size=14)) for _ in range(docs)
        ])
        for _ in range(n_clients)
    ]


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_bench(args: argparse.Namespace) -> dict:
    import numpy as np

    import jax

    from gfedntm_tpu.federation.client import Client
    from gfedntm_tpu.federation.server import FederatedServer
    from gfedntm_tpu.serving import ClosedLoopLoadGen, ServingPlane
    from gfedntm_tpu.serving.service import make_infer_stub
    from gfedntm_tpu.utils.observability import MetricsLogger

    tmp = tempfile.mkdtemp(prefix="serve_bench_")
    save_dir = os.path.join(tmp, "fed")
    port = _free_port()
    server_metrics = MetricsLogger(
        os.path.join(save_dir, "metrics.jsonl"), node="server"
    )
    server = FederatedServer(
        min_clients=args.clients, family="avitm",
        model_kwargs=dict(MODEL_KWARGS),
        max_iters=args.max_iters, save_dir=save_dir,
        metrics=server_metrics, checkpoint_every=0, journal_every=1,
    )
    server.start(f"[::]:{port}")
    client_metrics = MetricsLogger(validate=False)
    clients = [
        Client(
            client_id=c + 1, corpus=corpus,
            server_address=f"localhost:{port}",
            max_features=args.vocab,
            save_dir=os.path.join(tmp, f"c{c + 1}"),
            metrics=client_metrics,
        )
        for c, corpus in enumerate(
            _corpora(args.clients, args.docs, args.vocab)
        )
    ]
    threads = [
        threading.Thread(target=c.run, daemon=True, name=f"client{c.client_id}")
        for c in clients
    ]
    for t in threads:
        t.start()

    serve_metrics = MetricsLogger(
        os.path.join(tmp, "serve", "metrics.jsonl"), node="serve",
        validate=True,
    )
    plane = ServingPlane(
        save_dir, max_batch=args.max_batch, poll_s=args.poll_s,
        metrics=serve_metrics, ops_port=0,
    )
    plane.start("[::]:0")
    deadline = time.time() + 120.0
    while not plane.engine.ready and time.time() < deadline:
        time.sleep(0.1)
    if not plane.engine.ready:
        raise SystemExit("serving plane never became ready (no journal?)")
    vocab_size = len(plane.engine.vocab)

    # One generator PER WORKER: np.random.Generator is not thread-safe,
    # and the closed-loop workers draw concurrently.
    rngs = [
        np.random.default_rng(7 + i) for i in range(args.concurrency)
    ]

    def make_batch(worker: int, seq: int):
        b = args.docs_per_request
        return rngs[worker].integers(
            0, 3, size=(b, vocab_size)
        ).astype(np.float32)

    infer = make_infer_stub(f"localhost:{plane.bound_port}")
    gen = ClosedLoopLoadGen(
        infer, make_batch, concurrency=args.concurrency,
        duration_s=args.duration, metrics=serve_metrics,
    )
    summary = gen.run()

    plane.stop()
    server.stop()
    for c in clients:
        c.shutdown()
    serve_metrics.snapshot_registry()
    serve_metrics.close()
    server_metrics.close()
    client_metrics.close()
    infer.channel.close()

    reg = serve_metrics.registry

    def count(name):
        m = reg.get(name)
        return int(m.value) if m is not None else 0

    # The series in the artifact is rebuilt from the JSONL FILE, not from
    # the in-memory summary — proving the artifact reproducible from
    # telemetry alone (the same stream `summarize`/`report` read).
    from gfedntm_tpu.utils.observability import read_metrics

    series = [
        {k: rec.get(k) for k in (
            "t_s", "docs", "requests", "failures", "docs_per_s",
            "p50_ms", "p99_ms",
        )}
        for rec in read_metrics(serve_metrics.path)
        if rec.get("event") == "serve_load_window"
    ]
    p99 = summary["p99_ms"]
    artifact = {
        "bench": "serve",
        "rev": args.rev,
        "backend": jax.default_backend(),
        "clients": args.clients,
        "concurrency": args.concurrency,
        "docs_per_request": args.docs_per_request,
        "duration_s": summary["duration_s"],
        "target_p99_ms": args.target_p99_ms,
        "sustained_docs_per_s": round(summary["docs_per_s"], 1),
        "qps": round(summary["qps"], 1),
        "p50_ms": summary["p50_ms"],
        "p95_ms": summary["p95_ms"],
        "p99_ms": p99,
        "requests": summary["requests"],
        "failures": summary["failures"],
        "failure_samples": summary["failure_samples"],
        "model_rounds_seen": summary["model_rounds_seen"],
        "swaps": summary["swaps_observed"],
        "swaps_total": count("serving_swaps"),
        "swaps_refused": count("serving_swaps_refused"),
        "batch_fill": (
            reg.get("serving_batch_fill").value
            if reg.get("serving_batch_fill") else None
        ),
        "series": series,
        "acceptance": {
            "zero_failed_requests": summary["failures"] == 0,
            "hot_swaps_observed_ge_2": summary["swaps_observed"] >= 2,
            "p99_within_target": (
                p99 is not None and p99 <= args.target_p99_ms
            ),
        },
    }
    return bench_schema.require(artifact, "serve_bench")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rev", default="r01")
    p.add_argument("--clients", type=int, default=2)
    p.add_argument("--docs", type=int, default=48,
                   help="training docs per client")
    p.add_argument("--vocab", type=int, default=60)
    p.add_argument("--max_iters", type=int, default=400,
                   help="federation round cap (the run keeps publishing "
                        "rounds for the whole bench window)")
    p.add_argument("--duration", type=float, default=15.0,
                   help="measured closed-loop window seconds")
    p.add_argument("--concurrency", type=int, default=6)
    p.add_argument("--docs_per_request", type=int, default=8)
    p.add_argument("--max_batch", type=int, default=64)
    p.add_argument("--poll_s", type=float, default=0.25,
                   help="serving plane journal poll cadence")
    p.add_argument("--target_p99_ms", type=float, default=400.0,
                   help="the fixed p99 bound the sustained-docs/s "
                        "headline is reported at (default calibrated "
                        "for the shared-2-core CPU container, where the "
                        "co-located federation contends for both cores; "
                        "tighten on real accelerators)")
    p.add_argument("--out", default=OUT_PATH)
    args = p.parse_args(argv)

    artifact = run_bench(args)
    with open(args.out, "w") as fh:
        json.dump(artifact, fh, indent=1)
        fh.write("\n")
    print(json.dumps({k: v for k, v in artifact.items() if k != "series"}))
    print(f"wrote {args.out}")
    return 0 if all(artifact["acceptance"].values()) else 1


if __name__ == "__main__":
    sys.exit(main())
