#!/usr/bin/env python
"""DP overhead bench: the BENCH_DP artifact (ISSUE 18).

Measures what the server-side FedLD noise path costs where it actually
runs — :meth:`ServerAggregator._mean` — by timing identical aggregation
rounds with the noiser detached (the ``--dp off`` bitwise-no-op path)
and attached, over a realistic update plane (8 clients, ~200k float32
params). A second measurement times noise *generation* alone: the numpy
host oracle vs the device path (jax threefry, per-shard ``fold_in``),
plus the determinism check both paths must pass (draw ``i`` is a pure
function of ``(seed, i)``).

Acceptance bars (recorded in the artifact, asserted by the emitter):
- the noise path costs <= 10 ms absolute per round at the bench plane
  size (~200k params) — the bare weighted mean is sub-millisecond, so a
  relative bar would only measure the mean's smallness; the bound that
  matters is noise cost vs the >= 100 ms local-training floor of any
  real round, where <= 10 ms is noise (pun intended);
- both noise backends replay their streams exactly;
- both backends land within 5% of the calibrated std.

Usage:
    python scripts/dp_bench.py            # -> BENCH_DP_r01.json
    python scripts/dp_bench.py --quick    # fewer rounds, no artifact
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

OUT_PATH = os.path.join(REPO, "BENCH_DP_r01.json")

N_CLIENTS = 8
SHAPES = {  # ~200k params across a few tensors, AVITM-shaped
    "params/beta": (50, 2_000),
    "params/inf_w1": (2_000, 40),
    "params/inf_b1": (40,),
    "params/mu_w": (40, 50),
    "params/sigma_w": (40, 50),
    "num_batches": (),  # int passthrough
}


def _snapshots():
    import numpy as np

    rng = np.random.default_rng(7)
    snaps = []
    for i in range(N_CLIENTS):
        params = {
            k: rng.standard_normal(shape).astype(np.float32)
            if k != "num_batches" else np.array(3 + i, np.int32)
            for k, shape in SHAPES.items()
        }
        snaps.append((float(1 + i % 3), params))
    return snaps


def time_rounds(agg, snaps, rounds: int) -> float:
    """Median per-round wall ms of ``agg._mean`` over the snapshots."""
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        agg._mean(snaps)
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return times[len(times) // 2]


def bench_noise_gen(dim: int, std: float, reps: int) -> dict:
    """Host-oracle vs device noise generation: wall ms + the parity
    contract (exact per-path replay, std within 5% of calibration)."""
    import numpy as np

    from gfedntm_tpu.federation.device_agg import DeviceAggEngine, FlatPlane
    from gfedntm_tpu.privacy import host_noise_vector

    out: dict = {"dim": dim, "std": std}

    t0 = time.perf_counter()
    for i in range(reps):
        host = host_noise_vector(dim, std, seed=11, index=i)
    out["host_ms"] = round((time.perf_counter() - t0) * 1e3 / reps, 3)

    engine = DeviceAggEngine()
    plane = FlatPlane({"plane": np.zeros((dim,), np.float32)})
    engine.noise_vector(plane, std=std, seed=11, index=0)  # compile
    t0 = time.perf_counter()
    for i in range(reps):
        dev = engine.noise_vector(plane, std=std, seed=11, index=i)
    out["device_ms"] = round((time.perf_counter() - t0) * 1e3 / reps, 3)

    replay_host = host_noise_vector(dim, std, seed=11, index=reps - 1)
    replay_dev = engine.noise_vector(
        plane, std=std, seed=11, index=reps - 1
    )
    out["deterministic"] = bool(
        np.array_equal(host, replay_host)
        and np.array_equal(dev, replay_dev)
    )
    out["host_std_rel_err"] = round(
        abs(float(host.std()) - std) / std, 4
    )
    out["device_std_rel_err"] = round(
        abs(float(dev.std()) - std) / std, 4
    )
    return out


def main() -> int:
    quick = "--quick" in sys.argv[1:]
    rounds = 8 if quick else 40

    from gfedntm_tpu.federation.aggregation import make_aggregator
    from gfedntm_tpu.privacy import ServerNoiser, parse_dp
    from scripts import bench_schema

    snaps = _snapshots()
    spec = parse_dp("server", clip=0.5, sigma=0.6, seed=17)

    agg = make_aggregator("fedavg")
    time_rounds(agg, snaps, 3)  # warm caches before either timing
    noiseless_ms = time_rounds(agg, snaps, rounds)
    agg.noiser = ServerNoiser(spec)
    noised_ms = time_rounds(agg, snaps, rounds)
    agg.noiser = None
    noise_cost_ms = round(noised_ms - noiseless_ms, 3)
    overhead_pct = round(
        100.0 * (noised_ms - noiseless_ms) / max(noiseless_ms, 1e-9), 1
    )

    dim = sum(
        int(math.prod(s)) for k, s in SHAPES.items() if k != "num_batches"
    )
    noise_gen = bench_noise_gen(dim, std=spec.sigma * spec.clip,
                                reps=4 if quick else 20)

    acceptance = {
        "noise_cost_under_10ms": bool(noise_cost_ms <= 10.0),
        "noise_streams_deterministic": bool(noise_gen["deterministic"]),
        "std_calibrated_5pct": bool(
            noise_gen["host_std_rel_err"] <= 0.05
            and noise_gen["device_std_rel_err"] <= 0.05
        ),
    }
    result = bench_schema.require({
        "bench": "dp_overhead",
        "rev": "r01",
        "backend": "cpu",
        "n_clients": N_CLIENTS,
        "plane_elems": dim,
        "sigma": spec.sigma,
        "clip": spec.clip,
        "rounds": rounds,
        "noiseless_round_ms": round(noiseless_ms, 3),
        "noised_round_ms": round(noised_ms, 3),
        "noise_cost_ms": noise_cost_ms,
        "overhead_pct": overhead_pct,
        "noise_gen": noise_gen,
        "acceptance": acceptance,
    }, "dp_bench")

    print(json.dumps(result, indent=1))
    if quick:
        return 0
    with open(OUT_PATH, "w") as fh:
        json.dump(result, fh, indent=1)
        fh.write("\n")
    print(f"wrote {OUT_PATH}", file=sys.stderr)
    return 0 if all(acceptance.values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
